"""SOCKS proxy-chain tests (apps/socks.py): client -> proxy -> server
fetch relays — the modeled counterpart of the reference's tgen SOCKS
transport (shd-tgen-transport.c) and BASELINE.json config #3."""

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

from test_phold import MESH_TOPO

SERVER_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="serverport" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start"><data key="d0">80</data></node>
  </graph>
</graphml>"""


def socks_scenario(n_clients=2, count=3, size=40960, stop=40):
    # id layout: [0,1]=servers, [2,3]=proxies, [4..]=clients
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[
            HostSpec(id="server", quantity=2, processes=[
                ProcessSpec(plugin="tgen", start_time=10**9,
                            arguments=SERVER_GRAPH)]),
            HostSpec(id="proxy", quantity=2, processes=[
                ProcessSpec(plugin="socksproxy", start_time=10**9,
                            arguments="port=9050 server-port=80")]),
            HostSpec(id="client", quantity=n_clients, processes=[
                ProcessSpec(plugin="socksclient", start_time=2 * 10**9,
                            arguments=f"proxy-lo=2 proxy-hi=4 "
                                      f"proxy-port=9050 server-lo=0 "
                                      f"server-hi=2 size={size} "
                                      f"count={count} pause=500ms")]),
        ],
    )


def test_socks_fetches_complete():
    n = 2
    cfg = EngineConfig(num_hosts=4 + n, qcap=64, scap=16, obcap=64,
                       incap=128, chunk_windows=32)
    r = Simulation(socks_scenario(n_clients=n), engine_cfg=cfg).run()
    stats = r.stats
    clients = slice(4, 4 + n)
    # every client completed its fetches and reached the end state
    assert (stats[clients, defs.ST_XFER_DONE] == 3).all(), \
        stats[:, defs.ST_XFER_DONE]
    assert (stats[clients, defs.ST_APP_DONE] == 1).all()
    # responses actually traversed the relay: clients received the
    # bytes, and proxies both received (onward) and sent (relay) them
    assert (stats[clients, defs.ST_BYTES_RECV] >= 3 * 40960).all()
    proxies = slice(2, 4)
    assert stats[proxies, defs.ST_BYTES_RECV].sum() >= 6 * 40960
    assert stats[proxies, defs.ST_BYTES_SENT].sum() >= 6 * 40960
    # fetch latency was recorded
    assert r.summary()["mean_rtt_us"] > 0


def test_socks_deterministic():
    cfg = EngineConfig(num_hosts=5, qcap=64, scap=16, obcap=64,
                       incap=128, chunk_windows=32)
    r1 = Simulation(socks_scenario(n_clients=1), engine_cfg=cfg).run()
    r2 = Simulation(socks_scenario(n_clients=1), engine_cfg=cfg).run()
    assert np.array_equal(r1.stats, r2.stats)


def test_socks_three_hop_circuit():
    """hops=3 builds client -> entry -> middle -> exit -> server (the
    Tor circuit shape, BASELINE config #4): response bytes traverse
    every relay, so total relay-sent bytes ~= 3x the payload."""
    n = 2
    size = 20480
    cfg = EngineConfig(num_hosts=4 + n, qcap=64, scap=16, obcap=64,
                       incap=128, chunk_windows=32)
    scen = Scenario(
        stop_time=60 * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[
            HostSpec(id="server", quantity=2, processes=[
                ProcessSpec(plugin="tgen", start_time=10**9,
                            arguments=SERVER_GRAPH)]),
            HostSpec(id="relay", quantity=2, processes=[
                ProcessSpec(plugin="socksproxy", start_time=10**9,
                            arguments="port=9050 server-port=80 "
                                      "relay-lo=2 relay-hi=4")]),
            HostSpec(id="client", quantity=n, processes=[
                ProcessSpec(plugin="socksclient", start_time=2 * 10**9,
                            arguments=f"proxy-lo=2 proxy-hi=4 "
                                      f"proxy-port=9050 server-lo=0 "
                                      f"server-hi=2 size={size} hops=3 "
                                      "count=2 pause=1s")]),
        ],
    )
    r = Simulation(scen, engine_cfg=cfg).run()
    stats = r.stats
    clients = slice(4, 4 + n)
    assert (stats[clients, defs.ST_XFER_DONE] == 2).all(), \
        stats[:, defs.ST_XFER_DONE]
    assert (stats[clients, defs.ST_BYTES_RECV] >= 2 * size).all()
    # every response crossed 3 relay hops: relays collectively sent
    # ~3x what the clients received (entry+middle+exit forwarding)
    relay_sent = stats[2:4, defs.ST_BYTES_SENT].sum()
    client_got = stats[clients, defs.ST_BYTES_RECV].sum()
    assert relay_sent >= 3 * client_got * 9 // 10, (relay_sent,
                                                    client_got)
