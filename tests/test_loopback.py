"""Loopback TCP: same-host connections through the NIC loopback path
(the reference's tcp-loopback test variants, and the pipe/channel
equivalent for hosted apps — a self-connection is a byte channel)."""

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig
from shadow_tpu.hosting import HostedApp, register

from test_phold import MESH_TOPO


class SelfChannel(HostedApp):
    """Opens a listener and connects to itself over loopback, then
    PUTs bytes through — a pipe built from the real TCP stack."""

    def __init__(self, args):
        self.size = int(args) if args.strip() else 50000
        self.done = 0
        self.got_eof = 0

    def on_start(self, os):
        self.listener = os.tcp_listen(7000)
        self.client = os.tcp_connect(os.host_id, 7000)

    def on_connected(self, os, sock, **_identity):
        os.write(sock, self.size)
        os.close(sock)

    def on_sent(self, os, sock):
        self.done += 1

    def on_eof(self, os, sock):
        self.got_eof += 1
        os.close(sock)


register("test-selfchannel", SelfChannel)


def test_loopback_tcp_channel():
    scen = Scenario(
        stop_time=10 * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[HostSpec(id="solo", processes=[
            ProcessSpec(plugin="hosted:test-selfchannel",
                        start_time=10**9, arguments="50000")])],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=1, qcap=32, scap=8, obcap=16, incap=32, txqcap=8))
    app = sim.hosting.apps[0]
    report = sim.run()
    assert app.done == 1, "writer never saw all bytes acked"
    # both directions see EOF: the child reads the writer's FIN, and
    # the writer's socket sees the child's closing FIN
    assert app.got_eof == 2, app.got_eof
    assert report.stats[0, defs.ST_BYTES_RECV] == 50000
    # loopback never crosses the exchange
    assert report.stats[0, defs.ST_PKTS_DROP_NET] == 0


def test_loopback_stays_local():
    """A second, empty host proves loopback traffic never crosses the
    exchange (its stats stay zero)."""
    scen = Scenario(
        stop_time=10 * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[
            HostSpec(id="solo", processes=[
                ProcessSpec(plugin="hosted:test-selfchannel",
                            start_time=10**9, arguments="20000")]),
            HostSpec(id="bystander"),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=2, qcap=32, scap=8, obcap=16, incap=32, txqcap=8))
    report = sim.run()
    assert report.stats[0, defs.ST_BYTES_RECV] == 20000
    assert report.stats[1].sum() == 0


# --- round 3: the first-class pipe/channel object -------------------------

class PipeApp(HostedApp):
    """Moves bytes through an os.pipe() pair — the reference Channel
    shape (shd-channel.c): no TCP handshake, no ACK clock."""

    def __init__(self, args):
        self.size = int(args) if args.strip() else 50000
        self.got = 0
        self.eofs = 0

    def on_start(self, os):
        self.a, self.b = os.pipe()
        os.timer(1000)          # handles resolve before the next wake

    def on_timer(self, os, tag):
        os.write(self.a, self.size)
        os.close(self.a)

    def on_dgram(self, os, sock, src, sport, nbytes, aux):
        self.got += nbytes

    def on_eof(self, os, sock):
        self.eofs += 1
        os.close(sock)


register("test-pipeapp", PipeApp)


def _run_hosted(plugin, arg, size):
    scen = Scenario(
        stop_time=10 * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[HostSpec(id="solo", processes=[
            ProcessSpec(plugin=plugin, start_time=10**9,
                        arguments=str(size))])],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=1, qcap=32, scap=8, obcap=16, incap=32, txqcap=8))
    app = sim.hosting.apps[0]
    return app, sim.run()


def test_pipe_channel():
    size = 50000
    app, report = _run_hosted("hosted:test-pipeapp", "", size)
    assert app.got == size              # the byte count crossed
    assert app.eofs == 1                # close delivered EOF
    assert report.stats[0, defs.ST_BYTES_RECV] == size


def test_pipe_large_write_not_truncated():
    """A single write larger than the reference's 64 KiB channel
    buffer still moves the full modeled byte count (no silent
    truncation — delivery is immediate, so buffer backpressure is
    explicitly not modeled)."""
    size = 200_000
    app, report = _run_hosted("hosted:test-pipeapp", str(size), size)
    assert app.got == size
    assert report.stats[0, defs.ST_BYTES_RECV] == size


def test_pipe_avoids_tcp_machinery():
    """The point of the first-class channel: a pipe transfer costs a
    handful of events where the loopback-TCP stand-in pays the whole
    handshake/ACK/FIN machine."""
    size = 50000
    _, pipe_rep = _run_hosted("hosted:test-pipeapp", "", size)
    _, tcp_rep = _run_hosted("hosted:test-selfchannel", "", size)
    pipe_ev = int(pipe_rep.stats[0, defs.ST_EVENTS])
    tcp_ev = int(tcp_rep.stats[0, defs.ST_EVENTS])
    assert pipe_ev * 3 < tcp_ev, (pipe_ev, tcp_ev)
