"""Multiple processes per host (reference: the per-host process LIST,
shd-configuration.h:36-95; slave_addNewVirtualProcess shd-slave.c:293 —
the canonical tor+tgen host shape).

Each process slot has its own app kind/config/registers; sockets
remember their owning process and wakes route back to it. The
differential harness must hold: both engines run the same per-process
apps bit-identically.
"""

import numpy as np

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.pyengine import PyEngine
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

from test_tcp import poi_topology

CFG = dict(qcap=32, scap=12, obcap=16, incap=24, txqcap=12,
           chunk_windows=8)


def _mutual_scen(loss=0.0, stop=40):
    """Two hosts, each BOTH a server and a client of the other — the
    minimal process-list shape."""
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=poi_topology(loss=loss),
        hosts=[
            HostSpec(id="alpha", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=80"),
                ProcessSpec(plugin="bulk", start_time=2 * 10**9,
                            arguments="peer=beta port=80 size=80000 "
                                      "count=2 pause=1s")]),
            HostSpec(id="beta", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=80"),
                ProcessSpec(plugin="bulk", start_time=3 * 10**9,
                            arguments="peer=alpha port=80 size=50000 "
                                      "count=1 pause=1s")]),
        ],
    )


def _diff(scen_fn, n_hosts):
    from test_differential import TCP_COMPARE

    cfg = EngineConfig(num_hosts=n_hosts, **CFG)
    jax_stats = Simulation(scen_fn(), engine_cfg=cfg).run().stats
    py_stats = PyEngine(Simulation(scen_fn(), engine_cfg=cfg)).run()
    for st in TCP_COMPARE:
        assert np.array_equal(jax_stats[:, st], py_stats[:, st]), (
            f"stat {st} diverges:\n jax={jax_stats[:, st]}\n "
            f"py={py_stats[:, st]}")
    return jax_stats


def test_two_processes_mutual_transfer():
    stats = _diff(_mutual_scen, 2)
    # alpha's client pushed 2x80000 to beta's server; beta's client
    # pushed 1x50000 to alpha's server — both directions complete
    assert stats[0, defs.ST_BYTES_RECV] == 50000
    assert stats[1, defs.ST_BYTES_RECV] == 160000
    # client-side completion counted per host (client is proc 1)
    assert stats[0, defs.ST_APP_DONE] == 1
    assert stats[1, defs.ST_APP_DONE] == 1


def test_two_processes_lossy():
    stats = _diff(lambda: _mutual_scen(loss=0.03, stop=80), 2)
    assert stats[:, defs.ST_RETRANSMIT].sum() > 0
    assert stats[0, defs.ST_BYTES_RECV] == 50000
    assert stats[1, defs.ST_BYTES_RECV] == 160000


def test_mixed_kinds_per_host():
    """Different app FAMILIES in one host's process list: a UDP ping
    server next to a TCP bulk client (and the mirror on the peer)."""
    def scen():
        return Scenario(
            stop_time=30 * 10**9,
            topology_graphml=poi_topology(),
            hosts=[
                HostSpec(id="alpha", processes=[
                    ProcessSpec(plugin="pingserver", start_time=10**9,
                                arguments="port=8000"),
                    ProcessSpec(plugin="bulk", start_time=2 * 10**9,
                                arguments="peer=beta port=80 "
                                          "size=60000 count=1 "
                                          "pause=1s")]),
                HostSpec(id="beta", processes=[
                    ProcessSpec(plugin="bulkserver", start_time=10**9,
                                arguments="port=80"),
                    ProcessSpec(plugin="ping", start_time=2 * 10**9,
                                arguments="peer=alpha port=8000 "
                                          "interval=500ms size=96 "
                                          "count=8")]),
            ],
        )

    stats = _diff(scen, 2)
    # beta received the 60000-byte bulk stream AND 8 x 96-byte ping
    # echoes; alpha received the 8 ping requests
    assert stats[1, defs.ST_BYTES_RECV] == 60000 + 8 * 96
    assert stats[0, defs.ST_BYTES_RECV] == 8 * 96
    assert stats[1, defs.ST_RTT_COUNT] == 8           # all pings echoed
    assert stats[1, defs.ST_APP_DONE] == 1            # ping finished


def test_tgen_server_plus_bulk_client():
    """The verdict's reference shape: a tgen server graph and a bulk
    client in ONE host's process list (shd-slave.c:293 semantics)."""
    from test_tgen import SERVER_GRAPH

    def scen():
        return Scenario(
            stop_time=40 * 10**9,
            topology_graphml=poi_topology(),
            hosts=[
                HostSpec(id="combo", processes=[
                    ProcessSpec(plugin="tgen", start_time=10**9,
                                arguments=SERVER_GRAPH),
                    ProcessSpec(plugin="bulk", start_time=2 * 10**9,
                                arguments="peer=peer port=80 "
                                          "size=40000 count=1 "
                                          "pause=1s")]),
                HostSpec(id="peer", processes=[
                    ProcessSpec(plugin="bulkserver", start_time=10**9,
                                arguments="port=80")]),
            ],
        )

    stats = _diff(scen, 2)
    assert stats[1, defs.ST_BYTES_RECV] == 40000
    assert stats[0, defs.ST_APP_DONE] == 1            # bulk client done


def test_single_process_shapes_unchanged():
    """procs_per_host defaults to 1 and single-process scenarios keep
    the old behavior (regression guard for the [H, P] reshape)."""
    def scen():
        return Scenario(
            stop_time=20 * 10**9,
            topology_graphml=poi_topology(),
            hosts=[
                HostSpec(id="server", processes=[
                    ProcessSpec(plugin="bulkserver", start_time=10**9,
                                arguments="port=80")]),
                HostSpec(id="client", processes=[
                    ProcessSpec(plugin="bulk", start_time=2 * 10**9,
                                arguments="peer=server port=80 "
                                          "size=30000 count=1 "
                                          "pause=1s")]),
            ],
        )

    sim = Simulation(scen(), engine_cfg=EngineConfig(num_hosts=2, **CFG))
    assert sim.cfg.procs_per_host == 1
    rep = sim.run()
    assert rep.summary()["bytes_recv"] == 30000
