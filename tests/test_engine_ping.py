"""End-to-end engine tests: UDP ping/echo (BASELINE config #1 shape).

The analytic ground truth: on a single-PoI topology with a 20ms
self-loop and no loss, an echo RTT is exactly 2 x 20ms (+2ns of
delivery-notification delay), and no packets may drop.
"""

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation

ONE_POI = """
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="packetloss" attr.type="double" for="node" id="d0"/>
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4"/>
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3"/>
  <graph edgedefault="undirected">
    <node id="poi"><data key="d0">0.0</data>
      <data key="d3">2048</data><data key="d4">1024</data></node>
    <edge source="poi" target="poi"><data key="d7">20.0</data>
      <data key="d9">0.0</data></edge>
  </graph>
</graphml>
"""


def ping_scenario(count=5, stop=10):
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=ONE_POI,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="ping", start_time=2 * 10**9,
                            arguments="peer=server port=8000 interval=1s "
                                      f"size=64 count={count}")]),
        ],
    )


def test_ping_end_to_end():
    report = Simulation(ping_scenario()).run()
    s = report.summary()
    assert s["transfers_done"] == 5
    assert s["drop_net"] == 0 and s["drop_q"] == 0 and s["drop_buf"] == 0
    # 5 pings + 5 echoes
    assert s["pkts_sent"] == 10
    assert s["pkts_recv"] == 10
    # RTT = 2 x 20ms self-loop latency (+2ns notify delay, truncated in us)
    assert s["mean_rtt_us"] == pytest.approx(40_000, abs=1)
    # server received 5 x 64 payload bytes; client got the echoes
    assert s["bytes_recv"] == 2 * 5 * 64


def test_multi_client_ping_no_crosstalk():
    """Regression: several clients pinging one server in the same window
    must each get their own echo (the server's per-datagram replies ride
    the NIC transmit ring, not a per-socket destination register)."""
    scen = ping_scenario(count=4)
    scen.hosts[1].quantity = 3
    report = Simulation(scen).run()
    s = report.summary()
    assert s["transfers_done"] == 12
    assert s["pkts_sent"] == 24 and s["pkts_recv"] == 24
    # every client completed all its pings
    per_host_done = report.stats[:, defs.ST_XFER_DONE]
    assert (per_host_done[1:] == 4).all()


def test_ping_deterministic():
    r1 = Simulation(ping_scenario()).run()
    r2 = Simulation(ping_scenario()).run()
    assert np.array_equal(r1.stats, r2.stats)
    assert r1.windows == r2.windows
