"""Unmodified-binary hosting: the LD_PRELOAD shim dual-run test.

The reference's core capability is pointing at an existing binary and
running it inside the simulation via libc interposition
(src/preload/shd-interposer.c + the dual-build test pattern, SURVEY
§4). This test realizes exactly that check for the TPU build: ONE
pre-built epoll client binary (examples/plugins/epclient.c, plain
libc, no simulator headers) runs

  (a) natively against a real TCP sink on localhost, and
  (b) inside the simulator via LD_PRELOAD (hosting/shim_preload.c
      forwarding libc calls to hosting/shim.ShimApp),

and must report the SAME transfer count and byte total both ways.
"""

import os
import socket
import subprocess
import threading

import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLIENT_C = os.path.join(REPO, "examples/plugins/epclient.c")

TRANSFERS = 3
NBYTES = 100_000


@pytest.fixture(scope="module")
def client_bin(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("shim") / "epclient")
    subprocess.run(["cc", "-O2", "-o", out, CLIENT_C], check=True)
    return out


def run_native(client_bin):
    """The binary against a real localhost TCP sink."""
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    port = srv.getsockname()[1]
    srv.listen(16)

    def sink():
        for _ in range(TRANSFERS):
            c, _ = srv.accept()
            while c.recv(65536):
                pass
            c.close()

    t = threading.Thread(target=sink, daemon=True)
    t.start()
    out = subprocess.run(
        [client_bin, "127.0.0.1", str(port), str(NBYTES), str(TRANSFERS)],
        capture_output=True, text=True, timeout=60, check=True)
    srv.close()
    return out.stdout


def run_simulated(client_bin, tmp_path, simple_topology_xml):
    """The SAME binary under the simulator via the LD_PRELOAD shim."""
    out_path = str(tmp_path / "epclient.out")
    scen = Scenario(
        stop_time=60 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=8080")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="hosted:shim", start_time=2 * 10**9,
                            arguments=f"out={out_path} cmd={client_bin} "
                                      f"server 8080 {NBYTES} "
                                      f"{TRANSFERS}")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=2, qcap=32, scap=8, obcap=16, incap=32, txqcap=16,
        hostedcap=16, chunk_windows=8))
    report = sim.run()
    with open(out_path) as f:
        return f.read(), report


def test_same_binary_native_and_simulated(client_bin, tmp_path,
                                          simple_topology_xml):
    native = run_native(client_bin)
    assert f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}" in native

    simulated, report = run_simulated(client_bin, tmp_path,
                                      simple_topology_xml)
    # the unmodified binary completed the same work under simulation
    assert f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}" in simulated
    # and the simulated server side agrees (one XFER_DONE per upload)
    assert report.stats[0, defs.ST_XFER_DONE] == TRANSFERS
    assert report.stats[0, defs.ST_BYTES_RECV] == NBYTES * TRANSFERS
    # simulated wall-time line reports SIM time (clock interposition):
    # 3 transfers over a 20ms-latency link cannot finish in < 100ms of
    # simulated time, and the native run finished in milliseconds of
    # real time — the two "secs=" figures come from different clocks
    sim_secs = float(simulated.split("secs=")[1].split()[0])
    assert sim_secs > 0.05
