"""Unmodified-binary hosting: the LD_PRELOAD shim dual-run test.

The reference's core capability is pointing at an existing binary and
running it inside the simulation via libc interposition
(src/preload/shd-interposer.c + the dual-build test pattern, SURVEY
§4). This test realizes exactly that check for the TPU build: ONE
pre-built epoll client binary (examples/plugins/epclient.c, plain
libc, no simulator headers) runs

  (a) natively against a real TCP sink on localhost, and
  (b) inside the simulator via LD_PRELOAD (hosting/shim_preload.c
      forwarding libc calls to hosting/shim.ShimApp),

and must report the SAME transfer count and byte total both ways.
"""

import os
import socket
import subprocess
import threading

import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLIENT_C = os.path.join(REPO, "examples/plugins/epclient.c")

TRANSFERS = 3
NBYTES = 100_000


@pytest.fixture(scope="module")
def client_bin(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("shim") / "epclient")
    subprocess.run(["cc", "-O2", "-o", out, CLIENT_C], check=True)
    return out


def run_native(client_bin):
    """The binary against a real localhost TCP sink."""
    return run_native_argv([client_bin, "127.0.0.1", "{port}",
                            str(NBYTES), str(TRANSFERS)])


def run_native_argv(argv_tmpl):
    """Run any client argv against a real localhost TCP sink
    ({port} substituted with the sink's port)."""
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    port = srv.getsockname()[1]
    srv.listen(16)

    def sink():
        for _ in range(TRANSFERS):
            c, _ = srv.accept()
            while c.recv(65536):
                pass
            c.close()

    t = threading.Thread(target=sink, daemon=True)
    t.start()
    argv = [a.format(port=port) for a in argv_tmpl]
    out = subprocess.run(argv, capture_output=True, text=True,
                         timeout=60, check=True).stdout
    srv.close()
    return out


def run_simulated(client_bin, tmp_path, simple_topology_xml):
    """The SAME binary under the simulator via the LD_PRELOAD shim."""
    out_path = str(tmp_path / "epclient.out")
    scen = Scenario(
        stop_time=60 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=8080")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="hosted:shim", start_time=2 * 10**9,
                            arguments=f"out={out_path} cmd={client_bin} "
                                      f"server 8080 {NBYTES} "
                                      f"{TRANSFERS}")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=2, qcap=32, scap=8, obcap=16, incap=32, txqcap=16,
        hostedcap=16, chunk_windows=8))
    report = sim.run()
    with open(out_path) as f:
        return f.read(), report


def test_same_binary_native_and_simulated(client_bin, tmp_path,
                                          simple_topology_xml):
    native = run_native(client_bin)
    assert f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}" in native

    simulated, report = run_simulated(client_bin, tmp_path,
                                      simple_topology_xml)
    # the unmodified binary completed the same work under simulation
    assert f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}" in simulated
    # and the simulated server side agrees (one XFER_DONE per upload)
    assert report.stats[0, defs.ST_XFER_DONE] == TRANSFERS
    assert report.stats[0, defs.ST_BYTES_RECV] == NBYTES * TRANSFERS
    # simulated wall-time line reports SIM time (clock interposition):
    # 3 transfers over a 20ms-latency link cannot finish in < 100ms of
    # simulated time, and the native run finished in milliseconds of
    # real time — the two "secs=" figures come from different clocks
    sim_secs = float(simulated.split("secs=")[1].split()[0])
    assert sim_secs > 0.05


# --- round 3: the SERVER half of the dual-build pattern + UDP ------------

SERVER_C = os.path.join(REPO, "examples/plugins/epserver.c")
UPING_C = os.path.join(REPO, "examples/plugins/uping.c")


@pytest.fixture(scope="module")
def server_bin(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("shim") / "epserver")
    subprocess.run(["cc", "-O2", "-o", out, SERVER_C], check=True)
    return out


@pytest.fixture(scope="module")
def uping_bin(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("shim") / "uping")
    subprocess.run(["cc", "-O2", "-o", out, UPING_C], check=True)
    return out


def test_server_binary_native_and_simulated(client_bin, server_bin,
                                            tmp_path,
                                            simple_topology_xml):
    """The reference's FULL dual-build check: the same unmodified
    server binary (epserver) serves a real client natively AND
    simulated clients under the simulator — and on the simulated side
    BOTH ends are real binaries (epserver + epclient), each behind its
    own LD_PRELOAD shim."""
    # native: epserver + epclient over real loopback
    import socket as pysock
    s = pysock.socket()
    s.bind(("127.0.0.1", 0))
    free_port = s.getsockname()[1]
    s.close()
    srv = subprocess.Popen(
        [server_bin, str(free_port), str(TRANSFERS)],
        stdout=subprocess.PIPE, text=True)
    import time
    time.sleep(0.3)                      # let it reach listen()
    cli = subprocess.run(
        [client_bin, "127.0.0.1", str(free_port), str(NBYTES),
         str(TRANSFERS)],
        capture_output=True, text=True, timeout=60, check=True)
    srv_out, _ = srv.communicate(timeout=60)
    assert f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}" in srv_out
    assert f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}" in cli.stdout

    # simulated: SAME binaries, separate hosts, both behind the shim
    srv_path = str(tmp_path / "epserver.out")
    cli_path = str(tmp_path / "epclient.out")
    scen = Scenario(
        stop_time=120 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="hosted:shim", start_time=10**9,
                            arguments=f"out={srv_path} cmd={server_bin} "
                                      f"8080 {TRANSFERS}")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="hosted:shim", start_time=2 * 10**9,
                            arguments=f"out={cli_path} cmd={client_bin} "
                                      f"server 8080 {NBYTES} "
                                      f"{TRANSFERS}")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=2, qcap=32, scap=8, obcap=16, incap=32, txqcap=16,
        hostedcap=16, chunk_windows=8))
    report = sim.run()
    with open(srv_path) as f:
        srv_sim = f.read()
    with open(cli_path) as f:
        cli_sim = f.read()
    assert (f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}"
            in srv_sim), (srv_sim, cli_sim)
    assert (f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}"
            in cli_sim), cli_sim
    # the modeled network actually carried the bytes
    assert report.stats[0, defs.ST_BYTES_RECV] == NBYTES * TRANSFERS


# --- round 4: a THIRD-PARTY binary + hosted/modeled composition ----------

PY_CLIENT_SRC = """\
import socket, sys, time
host, port = sys.argv[1], int(sys.argv[2])
nbytes, count = int(sys.argv[3]), int(sys.argv[4])
t0 = time.monotonic()
total = 0
for _ in range(count):
    s = socket.create_connection((host, port))
    left = nbytes
    chunk = b"x" * 65536
    while left:
        sent = s.send(chunk[:min(left, 65536)])
        left -= sent
        total += sent
    s.close()
print(f"transfers={count} bytes={total} secs={time.monotonic()-t0:.3f}")
"""


def test_third_party_binary_python_interpreter(tmp_path,
                                               simple_topology_xml):
    """A binary containing NO code written for this repo — the stock
    CPython interpreter (several MB of foreign libc-using machine
    code) — runs a plain BLOCKING-socket script under the shim. The
    reference's credibility came from hosting foreign binaries (tor,
    bitcoin — shd-interposer.c exists to run them); this is that
    check at the scale this image allows. Blocking connect()/send()
    with no epoll exercises the round-4 park/reenter path (stock
    clients don't use nonblocking epoll loops)."""
    import sys as _sys

    script = str(tmp_path / "client.py")
    with open(script, "w") as f:
        f.write(PY_CLIENT_SRC)

    # native leg: the same interpreter + script against a real sink
    native = run_native_argv([_sys.executable, script, "127.0.0.1",
                              "{port}", str(NBYTES), str(TRANSFERS)])
    assert f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}" in native

    # simulated leg: same interpreter, same script, modeled network
    out_path = str(tmp_path / "pyclient.out")
    scen = Scenario(
        stop_time=60 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=8080")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="hosted:shim", start_time=2 * 10**9,
                            arguments=f"out={out_path} "
                                      f"cmd={_sys.executable} "
                                      f"{script} server 8080 {NBYTES} "
                                      f"{TRANSFERS}")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=2, qcap=32, scap=8, obcap=16, incap=32, txqcap=16,
        hostedcap=16, chunk_windows=8))
    report = sim.run()
    with open(out_path) as f:
        simulated = f.read()
    assert (f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}"
            in simulated), simulated
    assert report.stats[0, defs.ST_XFER_DONE] == TRANSFERS
    assert report.stats[0, defs.ST_BYTES_RECV] == NBYTES * TRANSFERS
    # the clock the script saw was SIMULATED time
    sim_secs = float(simulated.split("secs=")[1].split()[0])
    assert sim_secs > 0.05


def test_shim_binary_plus_modeled_process(client_bin, tmp_path,
                                          simple_topology_xml):
    """The reference's canonical host shape with a REAL binary: one
    host runs the shim-hosted epclient binary AND a modeled ping
    process side by side (tor + tgen, shd-configuration.h:36-95).
    Socket wakes must route to the right process (sk_proc through the
    hosted op replay)."""
    out_path = str(tmp_path / "epclient.out")
    scen = Scenario(
        stop_time=60 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=8080"),
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="ping", start_time=2 * 10**9,
                            arguments="peer=server port=8000 count=3 "
                                      "interval=1s size=64"),
                ProcessSpec(plugin="hosted:shim", start_time=3 * 10**9,
                            arguments=f"out={out_path} cmd={client_bin} "
                                      f"server 8080 {NBYTES} "
                                      f"{TRANSFERS}")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=2, qcap=32, scap=8, obcap=16, incap=32, txqcap=16,
        hostedcap=16, chunk_windows=8, procs_per_host=2))
    report = sim.run()
    with open(out_path) as f:
        out = f.read()
    # the real binary finished its uploads...
    assert f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}" in out, out
    assert report.stats[0, defs.ST_XFER_DONE] == TRANSFERS
    # ...and the modeled pinger ran beside it on the same host
    assert report.stats[1, defs.ST_RTT_COUNT] == 3


def test_udp_binary_against_modeled_server(uping_bin, tmp_path,
                                           simple_topology_xml):
    """UDP shim surface: an unmodified sendto/recvfrom binary pings
    the MODELED pingserver app and counts every echo."""
    out_path = str(tmp_path / "uping.out")
    count, size = 5, 256
    scen = Scenario(
        stop_time=60 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="hosted:shim", start_time=2 * 10**9,
                            arguments=f"out={out_path} cmd={uping_bin} "
                                      f"server 8000 {size} {count}")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=2, qcap=32, scap=8, obcap=16, incap=32, txqcap=16,
        hostedcap=16, chunk_windows=8, uses_tcp=False))
    report = sim.run()
    with open(out_path) as f:
        out = f.read()
    assert f"echoes={count} bytes={size * count}" in out, out
    assert report.stats[1, defs.ST_PKTS_RECV] == count


# --- round 4: REAL payload bytes between two hosted binaries -------------

PY_HTTP_SERVER_SRC = '''\
import hashlib
import socket
import sys
port, nreq = int(sys.argv[1]), int(sys.argv[2])
ls = socket.socket()
ls.bind(("0.0.0.0", port))
ls.listen(8)
served = 0
for _ in range(nreq):
    c, addr = ls.accept()
    req = b""
    while not req.endswith(b"\\n"):
        chunk = c.recv(4096)
        if not chunk:
            break
        req += chunk
    # request line: "GET <size> <seed>"
    parts = req.decode().split()
    size, seed = int(parts[1]), int(parts[2])
    body = bytes((seed + i) % 251 for i in range(size))
    hdr = "LEN %d SHA %s\\n" % (size, hashlib.sha256(body).hexdigest())
    c.sendall(hdr.encode() + body)
    c.close()
    served += 1
print("served=%d" % served)
'''

PY_HTTP_CLIENT_SRC = '''\
import hashlib
import socket
import sys
host, port, nreq = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
ok = 0
for i in range(nreq):
    size, seed = 1000 + 97 * i, i + 3
    s = socket.create_connection((host, port))
    s.sendall(("GET %d %d\\n" % (size, seed)).encode())
    data = b""
    while True:
        chunk = s.recv(65536)
        if not chunk:
            break
        data += chunk
    s.close()
    hdr, _, body = data.partition(b"\\n")
    parts = hdr.decode().split()
    expect = bytes((seed + j) % 251 for j in range(size))
    if (int(parts[1]) == len(body) == size
            and hashlib.sha256(body).hexdigest() == parts[3]
            and body == expect):
        ok += 1
print("ok=%d/%d" % (ok, nreq))
'''


def test_payload_parsing_binaries(tmp_path, simple_topology_xml):
    """REAL payload bytes end to end (round 4): two stock CPython
    interpreters — an HTTP-style server that PARSES each request line
    and serves content derived from it, and a client that verifies
    length, sha256 and exact bytes of every response. Impossible under
    zero-fill recv: this passes only if the bytes the client reads are
    the bytes the server wrote, delivered at the engine's modeled
    counts/timing (hosting.api.PayloadBroker keyed by the TCP 4-tuple
    off the establishment wakes — the materialization the reference
    gets for free from shared process memory, shd-interposer.c)."""
    import sys as _sys

    srv_script = str(tmp_path / "httpserver.py")
    cli_script = str(tmp_path / "httpclient.py")
    with open(srv_script, "w") as f:
        f.write(PY_HTTP_SERVER_SRC)
    with open(cli_script, "w") as f:
        f.write(PY_HTTP_CLIENT_SRC)

    nreq = 3
    srv_out = str(tmp_path / "srv.out")
    cli_out = str(tmp_path / "cli.out")
    scen = Scenario(
        stop_time=60 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="hosted:shim", start_time=10**9,
                            arguments=f"out={srv_out} "
                                      f"cmd={_sys.executable} "
                                      f"{srv_script} 8080 {nreq}")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="hosted:shim", start_time=2 * 10**9,
                            arguments=f"out={cli_out} "
                                      f"cmd={_sys.executable} "
                                      f"{cli_script} server 8080 "
                                      f"{nreq}")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=2, qcap=32, scap=8, obcap=16, incap=32, txqcap=16,
        hostedcap=16, chunk_windows=8))
    sim.run()
    with open(cli_out) as f:
        cli = f.read()
    with open(srv_out) as f:
        srv = f.read()
    assert f"ok={nreq}/{nreq}" in cli, (cli, srv)
    assert f"served={nreq}" in srv, (cli, srv)
