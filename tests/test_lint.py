"""simlint: the determinism / tracing-hazard / shim-conformance gate.

Tier-1 runs the full suite over the repo (the machine-checked
replacement for the reference's by-convention determinism discipline)
plus fixture tests proving each check family actually FIRES: a
wallclock call, a tracer `.item()`, a renumbered OP_* and a framing
edit must each fail the gate with exactly the named rule.

Deliberately jax-free: the linter is pure stdlib AST analysis, and the
tools.simlint loader imports it without touching the shadow_tpu
package __init__ (which imports jax).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.simlint import load  # noqa: E402

lint = load()
core = sys.modules["shadow_tpu.lint.core"]
determinism = sys.modules["shadow_tpu.lint.determinism"]
tracing = sys.modules["shadow_tpu.lint.tracing"]
shimproto = sys.modules["shadow_tpu.lint.shimproto"]

C_SHIM = os.path.join(REPO, "shadow_tpu/hosting/shim_preload.c")
PY_SHIM = os.path.join(REPO, "shadow_tpu/hosting/shim.py")


def _read(path):
    with open(path) as f:
        return f.read()


def make_repo(tmp_path, files):
    """Materialize a fixture repo: {relpath: content}."""
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)
    return str(tmp_path)


def run_cli(root, *extra):
    """python -m tools.simlint --root <root> from the real repo."""
    return subprocess.run(
        [sys.executable, "-m", "tools.simlint", "--root", str(root),
         *extra],
        cwd=REPO, capture_output=True, text=True)


# --- the gate: the repo itself is clean ------------------------------

def test_repo_is_clean_via_cli():
    """Acceptance: `python -m tools.simlint` exits 0 on the repo —
    every violation fixed, suppressed with justification, or
    baselined."""
    r = run_cli(REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_reachability_graph_is_alive():
    """Guard the call-graph machinery itself: if root detection or
    propagation silently broke, the repo scan would pass vacuously.
    The jitted core (window step, TCP kernels, app handlers) must be
    in the reachable set."""
    cache = core.SourceCache(REPO)
    project = tracing._Project(cache)
    fqns = {f.fqn for f in project.reachable}
    assert len(fqns) > 100, len(fqns)
    for expected in (
            "shadow_tpu.engine.window._pass_hot",
            "shadow_tpu.engine.window._step_hot",
            "shadow_tpu.engine.window.exchange",
            "shadow_tpu.parallel.shard._windows_body",
            "shadow_tpu.core.rowops.rget"):
        assert expected in fqns, expected
    assert any(f.startswith("shadow_tpu.net.tcp.") for f in fqns)


# --- fixture violations must FAIL the gate (acceptance) --------------

BAD_ENGINE = """\
import time
import os

def schedule(now):
    return now + time.time()

def key_of(h):
    return os.urandom(8)
"""

BAD_TRACED = """\
import jax
import jax.numpy as jnp

def helper(x):
    return x.item() + 1

def cold_helper(x):
    return x.item() + 2

@jax.jit
def step(x):
    return helper(x)
"""


def test_fixture_violations_fail_cli(tmp_path):
    """One fixture repo carrying all three acceptance violations: a
    wallclock call, a tracer .item() in jit-reachable code, and a
    renumbered OP_* in the shim pair -> exit 1 naming each rule."""
    py_shim = _read(PY_SHIM).replace("OP_GETNAME = 20",
                                     "OP_GETNAME = 23")
    assert "OP_GETNAME = 23" in py_shim
    root = make_repo(tmp_path, {
        "shadow_tpu/engine/bad.py": BAD_ENGINE,
        "shadow_tpu/engine/traced.py": BAD_TRACED,
        "shadow_tpu/hosting/shim_preload.c": _read(C_SHIM),
        "shadow_tpu/hosting/shim.py": py_shim,
    })
    r = run_cli(root)
    assert r.returncode == 1, r.stdout + r.stderr
    for rid in ("DET101", "DET103", "TRC101", "SHIM202"):
        assert rid in r.stdout, (rid, r.stdout)
    # reachability is selective: the unreferenced helper is not traced
    assert "cold_helper" not in r.stdout


def test_tracing_reachability_is_selective(tmp_path):
    root = make_repo(tmp_path,
                     {"shadow_tpu/engine/traced.py": BAD_TRACED})
    report = lint.run_lint(root)
    trc = [v for v in report["new"] if v.rule == "TRC101"]
    assert len(trc) == 1, report["new"]
    assert trc[0].line == 5  # helper, not cold_helper


# --- determinism rules (unit level) ----------------------------------

def det(src):
    return determinism.check_source("shadow_tpu/engine/x.py", src)


def test_det_wallclock_and_datetime():
    vs = det("import time\nfrom datetime import datetime\n"
             "def f():\n    a = time.monotonic()\n"
             "    b = datetime.now()\n    return a, b\n")
    assert [v.rule for v in vs] == ["DET101", "DET101"]


def test_det_unseeded_rng():
    vs = det("import random\nimport numpy as np\n"
             "def f():\n    a = random.random()\n"
             "    rng = np.random.default_rng()\n"
             "    b = np.random.rand(3)\n    return a, rng, b\n")
    assert [v.rule for v in vs] == ["DET102"] * 3


def test_det_seeded_rng_ok():
    vs = det("import numpy as np\nimport random\n"
             "def f(seed):\n    rng = np.random.default_rng(seed)\n"
             "    r = random.Random(seed)\n"
             "    s = np.random.RandomState(seed ^ 7)\n"
             "    return rng, r, s\n")
    assert vs == []


def test_det_hash_used_vs_probe():
    # result used -> DET104; bare-statement hashability probe -> ok
    vs = det("def f(k):\n    return hash(k) % 8\n")
    assert [v.rule for v in vs] == ["DET104"]
    vs = det("def probe(sh):\n    try:\n        hash(sh)\n"
             "    except TypeError:\n        sh = None\n"
             "    return sh\n")
    assert vs == []
    assert det("def f(n):\n    return hash(3)\n") == []


def test_det_set_iteration():
    vs = det("def f(xs):\n    s = set(xs)\n"
             "    for x in s:\n        yield x\n")
    assert [v.rule for v in vs] == ["DET105"]
    assert det("def f(xs):\n    s = set(xs)\n"
               "    for x in sorted(s):\n        yield x\n") == []
    vs = det("def f(a, b):\n    return [x for x in set(a) | set(b)]\n")
    assert [v.rule for v in vs] == ["DET105"]


# --- tracing rules beyond TRC101 (unit level) ------------------------

TRC_PANEL = """\
import jax
import jax.numpy as jnp
import numpy as np

GLOBAL_TABLE = {}

def helper(x):
    if jnp.any(x > 0):
        x = x + GLOBAL_TABLE.get("k", 0)
    y = float(x)
    z = np.asarray(x)
    return y, z

def mk(x, opts=[1, 2]):
    return x

@jax.jit
def step(x):
    return helper(x)

fast = jax.jit(mk, static_argnums=1)
"""


def test_tracing_rule_panel(tmp_path):
    root = make_repo(tmp_path,
                     {"shadow_tpu/engine/panel.py": TRC_PANEL})
    report = lint.run_lint(root)
    rules = sorted(v.rule for v in report["new"])
    assert rules == ["TRC102", "TRC103", "TRC104", "TRC105",
                     "TRC106"], report["new"]


# --- suppression & baseline workflow ---------------------------------

def test_inline_suppression_requires_justification(tmp_path):
    ok = ("import os\n\ndef f():\n"
          "    return os.urandom(8)  # simlint: ok DET103 -- fixture\n")
    root = make_repo(tmp_path, {"shadow_tpu/engine/a.py": ok})
    report = lint.run_lint(root)
    assert report["exit_code"] == 0 and report["suppressed"] == 1

    bare = ("import os\n\ndef f():\n"
            "    return os.urandom(8)  # simlint: ok DET103\n")
    root2 = make_repo(tmp_path / "b", {"shadow_tpu/engine/a.py": bare})
    report = lint.run_lint(root2)
    assert report["exit_code"] == 1
    assert [v.rule for v in report["new"]] == ["LNT001"]

    # --fix-baseline must NOT pin the LNT001 away: the justification
    # requirement survives the one-command adoption path
    lint.run_lint(root2, fix_baseline=True)
    report = lint.run_lint(root2)
    assert report["exit_code"] == 1
    assert [v.rule for v in report["new"]] == ["LNT001"]


def test_baseline_pins_and_goes_stale(tmp_path):
    src = "import os\n\ndef f():\n    return os.urandom(8)\n"
    root = make_repo(tmp_path, {"shadow_tpu/engine/a.py": src})
    baseline = os.path.join(root, "tools/simlint/baseline.json")

    report = lint.run_lint(root)
    assert report["exit_code"] == 1
    assert [v.rule for v in report["new"]] == ["DET103"]

    # --fix-baseline adopts the debt in one command...
    report = lint.run_lint(root, fix_baseline=True)
    assert report["exit_code"] == 0
    entries = json.load(open(baseline))["entries"]
    assert len(entries) == 1 and entries[0]["rule"] == "DET103"

    # ...after which the gate is clean
    report = lint.run_lint(root)
    assert report["exit_code"] == 0 and report["baselined"] == 1

    # a SECOND violation of the same shape still fails (counts pin)
    src2 = src + "\ndef g():\n    return os.urandom(8)\n"
    (tmp_path / "shadow_tpu/engine/a.py").write_text(src2)
    report = lint.run_lint(root)
    assert report["exit_code"] == 1 and len(report["new"]) == 1

    # fixing the violation makes the baseline entry STALE -> fail
    (tmp_path / "shadow_tpu/engine/a.py").write_text(
        "def f():\n    return b'\\x00' * 8\n")
    report = lint.run_lint(root)
    assert report["exit_code"] == 1
    assert [v.rule for v in report["stale"]] == ["LNT002"]


def test_baseline_distinguishes_line0_violations(tmp_path):
    """SHIM2xx violations carry no source line; they must key by
    message so a pinned conformance finding cannot silently absorb a
    later, DIFFERENT drift of the same rule."""
    c = _read(C_SHIM)
    root = make_repo(tmp_path, {
        "shadow_tpu/hosting/shim_preload.c":
            c.replace(" OP_GETNAME, OP_VIOLATION,", " OP_GETNAME,", 1),
        "shadow_tpu/hosting/shim.py": _read(PY_SHIM),
    })
    lint.run_lint(root, fix_baseline=True)
    assert lint.run_lint(root)["exit_code"] == 0
    # a different missing opcode is NOT covered by the pinned one
    (tmp_path / "shadow_tpu/hosting/shim_preload.c").write_text(
        c.replace(" OP_RANDOM, OP_GETNAME,", " OP_GETNAME,", 1))
    report = lint.run_lint(root)
    assert report["exit_code"] == 1
    assert any(v.rule == "SHIM201" and "OP_RANDOM" in v.message
               for v in report["new"]), report["new"]
    assert report["stale"], "old pin must go stale"


# --- shim protocol conformance (the satellite fixtures) --------------

@pytest.fixture(scope="module")
def shim_texts():
    return _read(C_SHIM), _read(PY_SHIM)


def test_conformance_clean_on_repo(shim_texts):
    c, py = shim_texts
    assert shimproto.check_texts(c, py) == []


def test_conformance_renumbered_opcode(shim_texts):
    c, py = shim_texts
    bad = py.replace("OP_GETNAME = 20", "OP_GETNAME = 23")
    assert bad != py
    vs = shimproto.check_texts(c, bad)
    assert len(vs) == 1 and vs[0].rule == "SHIM202", vs
    assert "OP_GETNAME" in vs[0].message


def test_conformance_missing_opcode(shim_texts):
    c, py = shim_texts
    bad_c = c.replace(" OP_GETNAME, OP_VIOLATION,",
                      " OP_GETNAME,", 1)
    assert bad_c != c
    vs = shimproto.check_texts(bad_c, py)
    assert len(vs) == 1 and vs[0].rule == "SHIM201", vs
    assert "OP_VIOLATION" in vs[0].message


def test_conformance_framing_mismatch(shim_texts):
    c, py = shim_texts
    bad = py.replace("OP_RECVFROM\n  responses never carry payload",
                     "OP_RECVFROM\n  responses carry r0 trailing "
                     "payload bytes")
    assert bad != py
    vs = shimproto.check_texts(c, bad)
    assert len(vs) == 1 and vs[0].rule == "SHIM211", vs
    assert "OP_RECVFROM" in vs[0].message


def test_conformance_struct_layout(shim_texts):
    c, py = shim_texts
    bad_c = c.replace("struct req { int32_t op; int32_t a; "
                      "int64_t b; int64_t c;",
                      "struct req { int32_t op; int32_t a; "
                      "int64_t b; int32_t c;")
    assert bad_c != c
    vs = shimproto.check_texts(bad_c, py)
    assert len(vs) == 1 and vs[0].rule == "SHIM210", vs
    assert "REQ" in vs[0].message


def test_conformance_doc_fmt_token(shim_texts):
    c, py = shim_texts
    bad = py.replace("<qq> (fd, events) pairs",
                     "<qqq8s> (fd, events) pairs")
    assert bad != py
    vs = shimproto.check_texts(c, bad)
    assert any(v.rule == "SHIM212" for v in vs), vs


# --- the gate is genuinely dependency-free ---------------------------

def test_gate_runs_without_jax(tmp_path):
    """The CI simlint job runs on a box with NO jax installed, and
    the gate's speed budget assumes no jax import. Regression test
    for the `from . import submodule` fromlist path, whose C-level
    re-import walked to the root `shadow_tpu` package (executing its
    jax import — or crashing where jax is absent). Simulated here by
    blocking jax at the finder level in a subprocess."""
    (tmp_path / "sitecustomize.py").write_text(
        "import sys\n"
        "class _Block:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ModuleNotFoundError(\n"
        "                'jax import blocked by test', name=name)\n"
        "        return None\n"
        "sys.meta_path.insert(0, _Block())\n")
    env = dict(os.environ, PYTHONPATH=str(tmp_path))
    r = subprocess.run([sys.executable, "-m", "tools.simlint"],
                       cwd=REPO, capture_output=True, text=True,
                       env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


# --- machine-readable report schema stays stable ---------------------

def test_json_report_schema_stable(tmp_path):
    """CI and downstream consumers parse `--json`; growing the suite
    (the PR-11 stateflow family) must not change the schema. Checked
    both clean (the repo) and with violations present."""
    r = run_cli(REPO, "--json")
    data = json.loads(r.stdout)
    assert sorted(data) == ["allowed", "baseline_path", "baselined",
                            "exit_code", "new", "stale", "suppressed",
                            "total"]
    assert data["exit_code"] == 0

    root = make_repo(tmp_path,
                     {"shadow_tpu/engine/bad.py": BAD_ENGINE})
    r = run_cli(root, "--json")
    data = json.loads(r.stdout)
    assert data["exit_code"] == 1 and data["new"]
    for v in data["new"] + data["stale"]:
        assert sorted(v) == ["file", "line", "message", "rule",
                             "snippet"]


# --- rule catalog stays documented -----------------------------------

def test_rules_have_docs_and_catalog_entry():
    doc = _read(os.path.join(REPO, "docs/static-analysis.md"))
    for rid in core.RULES:
        assert rid in doc, f"{rid} missing from docs/static-analysis.md"
