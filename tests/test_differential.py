"""Differential tests: compiled array engine vs pure-Python engine.

The TPU realization of the reference's dual-run test pattern (SURVEY
§4: every test runs natively AND under shadow; agreement = the
emulation is faithful). Here the same scenario runs under the compiled
window program and under engine.pyengine's auditable heap loop; the
per-host stats must match BIT FOR BIT — queues, NIC accounting,
exchange budgets, loss rolls, RNG streams and window advance all agree
or some engine behavior diverged.
"""

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.pyengine import PyEngine
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

from test_phold import MESH_TOPO, phold_scenario

LOSSY_TOPO = MESH_TOPO.replace('<data key="d9">0.0</data>',
                               '<data key="d9">0.02</data>')

CFG = dict(qcap=16, scap=4, obcap=8, incap=16, txqcap=8, chunk_windows=8)

COMPARE = [defs.ST_EVENTS, defs.ST_PKTS_SENT, defs.ST_PKTS_RECV,
           defs.ST_PKTS_DROP_NET, defs.ST_PKTS_DROP_BUF,
           defs.ST_PKTS_DROP_Q, defs.ST_BYTES_RECV, defs.ST_OUTBOX_DROP,
           defs.ST_EQ_FULL_LOCAL, defs.ST_TXQ_DROP, defs.ST_RTT_SUM_US,
           defs.ST_RTT_COUNT, defs.ST_XFER_DONE, defs.ST_APP_DONE,
           defs.ST_SOCK_FAIL]


def _diff(scenario_fn, n_hosts):
    jax_stats = Simulation(scenario_fn(),
                           engine_cfg=EngineConfig(num_hosts=n_hosts,
                                                   **CFG)).run().stats
    py_stats = PyEngine(Simulation(scenario_fn(),
                                   engine_cfg=EngineConfig(
                                       num_hosts=n_hosts, **CFG))).run()
    for st in COMPARE:
        assert np.array_equal(jax_stats[:, st], py_stats[:, st]), (
            f"stat {st} diverges:\n jax={jax_stats[:, st]}\n "
            f"py={py_stats[:, st]}")


def test_differential_ping(simple_topology_xml):
    def scen():
        return Scenario(
            stop_time=8 * 10**9,
            topology_graphml=simple_topology_xml,
            hosts=[
                HostSpec(id="srv", processes=[
                    ProcessSpec(plugin="pingserver", start_time=10**9,
                                arguments="port=8000")]),
                HostSpec(id="cli", processes=[
                    ProcessSpec(plugin="ping", start_time=2 * 10**9,
                                arguments="peer=srv port=8000 "
                                          "interval=700ms size=96 "
                                          "count=6")]),
            ],
        )

    _diff(scen, 2)


def test_differential_phold():
    _diff(lambda: phold_scenario(n=12, stop=4), 12)


def test_differential_phold_lossy():
    def scen():
        return Scenario(
            stop_time=4 * 10**9,
            topology_graphml=LOSSY_TOPO,
            hosts=[HostSpec(id="node", quantity=12, processes=[
                ProcessSpec(plugin="phold", start_time=10**9,
                            arguments="port=9000 mean=150ms size=64 "
                                      "init=2")])],
        )

    _diff(scen, 12)
