"""Differential tests: compiled array engine vs pure-Python engine.

The TPU realization of the reference's dual-run test pattern (SURVEY
§4: every test runs natively AND under shadow; agreement = the
emulation is faithful). Here the same scenario runs under the compiled
window program and under engine.pyengine's auditable heap loop; the
per-host stats must match BIT FOR BIT — queues, NIC accounting,
exchange budgets, loss rolls, RNG streams and window advance all agree
or some engine behavior diverged.
"""

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.pyengine import PyEngine
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

from test_phold import MESH_TOPO, phold_scenario

LOSSY_TOPO = MESH_TOPO.replace('<data key="d9">0.0</data>',
                               '<data key="d9">0.02</data>')

CFG = dict(qcap=16, scap=4, obcap=8, incap=16, txqcap=8, chunk_windows=8)

COMPARE = [defs.ST_EVENTS, defs.ST_PKTS_SENT, defs.ST_PKTS_RECV,
           defs.ST_PKTS_DROP_NET, defs.ST_PKTS_DROP_BUF,
           defs.ST_PKTS_DROP_Q, defs.ST_BYTES_RECV, defs.ST_OUTBOX_DROP,
           defs.ST_EQ_FULL_LOCAL, defs.ST_TXQ_DROP, defs.ST_RTT_SUM_US,
           defs.ST_RTT_COUNT, defs.ST_XFER_DONE, defs.ST_APP_DONE,
           defs.ST_SOCK_FAIL]


def _diff(scenario_fn, n_hosts):
    jax_stats = Simulation(scenario_fn(),
                           engine_cfg=EngineConfig(num_hosts=n_hosts,
                                                   **CFG)).run().stats
    py_stats = PyEngine(Simulation(scenario_fn(),
                                   engine_cfg=EngineConfig(
                                       num_hosts=n_hosts, **CFG))).run()
    for st in COMPARE:
        assert np.array_equal(jax_stats[:, st], py_stats[:, st]), (
            f"stat {st} diverges:\n jax={jax_stats[:, st]}\n "
            f"py={py_stats[:, st]}")


def test_differential_ping(simple_topology_xml):
    def scen():
        return Scenario(
            stop_time=8 * 10**9,
            topology_graphml=simple_topology_xml,
            hosts=[
                HostSpec(id="srv", processes=[
                    ProcessSpec(plugin="pingserver", start_time=10**9,
                                arguments="port=8000")]),
                HostSpec(id="cli", processes=[
                    ProcessSpec(plugin="ping", start_time=2 * 10**9,
                                arguments="peer=srv port=8000 "
                                          "interval=700ms size=96 "
                                          "count=6")]),
            ],
        )

    _diff(scen, 2)


def test_differential_phold():
    _diff(lambda: phold_scenario(n=12, stop=4), 12)


def test_differential_phold_lossy():
    def scen():
        return Scenario(
            stop_time=4 * 10**9,
            topology_graphml=LOSSY_TOPO,
            hosts=[HostSpec(id="node", quantity=12, processes=[
                ProcessSpec(plugin="phold", start_time=10**9,
                            arguments="port=9000 mean=150ms size=64 "
                                      "init=2")])],
        )

    _diff(scen, 12)


# --- TCP tier (the reference's tcp test matrix idea: same scenario,
# lossless AND lossy, both engines must agree bit for bit — the dual
# run applied to the hard path: handshake, windows, SACK recovery,
# RTO go-back-N, cubic, close) --------------------------------------------

TCP_COMPARE = COMPARE + [defs.ST_BYTES_SENT, defs.ST_RETRANSMIT,
                         defs.ST_SACK_RENEGE, defs.ST_TGEN_DROP,
                         defs.ST_TGEN_ABORT]


def _diff_tcp(scenario_fn, n_hosts, cfg=None):
    cfg = dict(CFG) if cfg is None else cfg
    jax_stats = Simulation(scenario_fn(),
                           engine_cfg=EngineConfig(num_hosts=n_hosts,
                                                   **cfg)).run().stats
    py_stats = PyEngine(Simulation(scenario_fn(),
                                   engine_cfg=EngineConfig(
                                       num_hosts=n_hosts, **cfg))).run()
    for st in TCP_COMPARE:
        assert np.array_equal(jax_stats[:, st], py_stats[:, st]), (
            f"stat {st} diverges:\n jax={jax_stats[:, st]}\n "
            f"py={py_stats[:, st]}")
    return jax_stats


def _bulk_scen(loss, size, count, clients=1, stop=60):
    from test_tcp import poi_topology

    def scen():
        return Scenario(
            stop_time=stop * 10**9,
            topology_graphml=poi_topology(loss=loss),
            hosts=[
                HostSpec(id="server", processes=[
                    ProcessSpec(plugin="bulkserver", start_time=10**9,
                                arguments="port=80")]),
                HostSpec(id="client", quantity=clients, processes=[
                    ProcessSpec(plugin="bulk", start_time=2 * 10**9,
                                arguments=f"peer=server port=80 "
                                          f"size={size} count={count} "
                                          f"pause=1s")]),
            ],
        )

    return scen


def test_differential_tcp_lossless():
    stats = _diff_tcp(_bulk_scen(loss=0.0, size=120_000, count=2), 2)
    assert stats[:, defs.ST_XFER_DONE].sum() == 4   # both ends, 2 xfers


def test_differential_tcp_lossy():
    """5% loss: handshake retries, SACK fast recovery, RTO go-back-N,
    FIN retransmission — all must agree bit for bit."""
    stats = _diff_tcp(_bulk_scen(loss=0.05, size=120_000, count=2,
                                 stop=90), 2)
    assert stats[:, defs.ST_RETRANSMIT].sum() > 0   # loss actually bit


def test_differential_tgen_web(simple_topology_xml):
    """tgen behavior graph (GET walk + pauses) over a lossy link: the
    walk machinery, transfer tags, watchdogs and server children agree
    across engines."""
    from test_tgen import SERVER_GRAPH, WEB_GRAPH

    lossy = simple_topology_xml.replace('<data key="d9">0.0</data>',
                                        '<data key="d9">0.03</data>')

    def scen():
        return Scenario(
            stop_time=40 * 10**9,
            topology_graphml=lossy,
            hosts=[
                HostSpec(id="server1", processes=[
                    ProcessSpec(plugin="tgen", start_time=10**9,
                                arguments=SERVER_GRAPH)]),
                HostSpec(id="server2", processes=[
                    ProcessSpec(plugin="tgen", start_time=10**9,
                                arguments=SERVER_GRAPH)]),
                HostSpec(id="web", quantity=2, processes=[
                    ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                                arguments=WEB_GRAPH)]),
            ],
        )

    stats = _diff_tcp(scen, 4, cfg=dict(qcap=24, scap=6, obcap=12,
                                        incap=16, txqcap=8,
                                        chunk_windows=8))
    assert stats[2:, defs.ST_XFER_DONE].sum() > 0


# --- SOCKS proxy chains (the at-scale flagship app, BASELINE #3/#4
# shape at toy size: clients fetch through 1- and 2-hop relay circuits;
# CONNECT tags, relay pairing, streamed relay writes and pair teardown
# must agree bit for bit) ---------------------------------------------------

def _socks_scen(loss=0.0, hops=1, clients=3, stop=45):
    from test_tcp import poi_topology

    def scen():
        return Scenario(
            stop_time=stop * 10**9,
            topology_graphml=poi_topology(loss=loss),
            hosts=[
                # ids 0-1: target servers; 2-4: relays; 5+: clients
                HostSpec(id="server", quantity=2, processes=[
                    ProcessSpec(plugin="bulkserver", start_time=10**9,
                                arguments="port=80")]),
                HostSpec(id="relay", quantity=3, processes=[
                    ProcessSpec(plugin="socksproxy", start_time=10**9,
                                arguments="port=9050 server-port=80 "
                                          "relay-lo=2 relay-hi=5")]),
                HostSpec(id="client", quantity=clients, processes=[
                    ProcessSpec(plugin="socksclient", start_time=2 * 10**9,
                                arguments=f"proxy-lo=2 proxy-hi=5 "
                                          f"proxy-port=9050 server-lo=0 "
                                          f"server-hi=2 size=30000 "
                                          f"count=2 pause=1s "
                                          f"hops={hops}")]),
            ],
        )

    return scen


SOCKS_CFG = dict(qcap=32, scap=12, obcap=16, incap=24, txqcap=12,
                 chunk_windows=8)
SOCKS_COMPARE = TCP_COMPARE + [defs.ST_CHAIN_SHORT]


def _diff_socks(scenario_fn, n_hosts):
    jax_stats = Simulation(scenario_fn(),
                           engine_cfg=EngineConfig(num_hosts=n_hosts,
                                                   **SOCKS_CFG)).run().stats
    py_stats = PyEngine(Simulation(scenario_fn(),
                                   engine_cfg=EngineConfig(
                                       num_hosts=n_hosts,
                                       **SOCKS_CFG))).run()
    for st in SOCKS_COMPARE:
        assert np.array_equal(jax_stats[:, st], py_stats[:, st]), (
            f"stat {st} diverges:\n jax={jax_stats[:, st]}\n "
            f"py={py_stats[:, st]}")
    return jax_stats


def test_differential_socks():
    """Single-hop circuits: client -> relay -> server."""
    stats = _diff_socks(_socks_scen(hops=1), 8)
    # every client finished its 2 fetches
    assert (stats[5:, defs.ST_APP_DONE] == 1).all()


def test_differential_socks_multihop_lossy():
    """2-hop circuits over a 2%-loss link: chain extension plus loss
    recovery on every leg."""
    stats = _diff_socks(_socks_scen(loss=0.02, hops=2, stop=90), 8)
    assert stats[:, defs.ST_RETRANSMIT].sum() > 0
    assert stats[5:, defs.ST_XFER_DONE].sum() > 0
