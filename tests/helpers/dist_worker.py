"""Worker process for the multi-process (DCN-tier) test.

Launched N times by tests/test_distributed.py over loopback TCP:
    python dist_worker.py <coordinator> <num_procs> <proc_id> <out.npy>
Each process contributes 2 virtual CPU devices; the global mesh spans
all processes — the same shape a real multi-host TPU deployment has
(ICI within a process's slice, DCN between processes).
"""

import os
import sys


def main():
    coord, nproc, pid, out = sys.argv[1:5]
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "..", ".."))
    sys.path.insert(0, here)
    from shadow_tpu.parallel import dist

    dist.init(coord, int(nproc), int(pid), local_device_count=2)

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from shadow_tpu.engine.sim import Simulation
    from scenario_phold import make_scenario, make_cfg

    scen = make_scenario()
    cfg = make_cfg()
    mesh = dist.global_mesh()
    assert len(mesh.devices.flat) == 2 * int(nproc)
    r = Simulation(scen, engine_cfg=cfg).run(mesh=mesh)
    if int(pid) == 0:
        np.save(out, r.stats)
    print(f"proc {pid}: {r.events} events", flush=True)


if __name__ == "__main__":
    main()
