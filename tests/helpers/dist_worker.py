"""Worker process for the multi-process (DCN-tier) tests.

Launched N times by tests/test_distributed.py over loopback TCP:
    python dist_worker.py <coordinator> <num_procs> <proc_id> <out.npy>
        [--ckpt <path>] [--resume] [--digest <path>] [--crash-ns N]
Each process contributes 2 virtual CPU devices; the global mesh spans
all processes — the same shape a real multi-host TPU deployment has
(ICI within a process's slice, DCN between processes).

--ckpt: checkpoint every simulated second into <path> while running
(process 0 writes the global snapshot). --resume: restore from <path>
instead of starting fresh. --digest: record a determinism digest
chain at cadence 8 (every process pulls the global state — the
per-record allgather — and process 0 writes the chain file).
--crash-ns: arm the durability CrashHook — every process SIGKILLs
itself at the first chunk boundary at/after that simulated time
(deterministic, so all processes die at the same logical point; no
fire-once guard — the resume phase simply omits the flag).
"""

import os
import sys


def main():
    coord, nproc, pid, out = sys.argv[1:5]
    rest = sys.argv[5:]
    ckpt = rest[rest.index("--ckpt") + 1] if "--ckpt" in rest else None
    resume = "--resume" in rest
    pcap = rest[rest.index("--pcap") + 1] if "--pcap" in rest else None
    digest = (rest[rest.index("--digest") + 1]
              if "--digest" in rest else None)
    if "--crash-ns" in rest:
        os.environ["SHADOW_TPU_CRASH_SIM_NS"] = (
            rest[rest.index("--crash-ns") + 1])
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "..", ".."))
    sys.path.insert(0, here)
    from shadow_tpu.parallel import dist

    dist.init(coord, int(nproc), int(pid), local_device_count=2)

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from shadow_tpu.engine.sim import Simulation
    from scenario_phold import make_scenario, make_cfg

    scen = make_scenario(pcap=bool(pcap))
    cfg = make_cfg()
    mesh = dist.global_mesh()
    assert len(mesh.devices.flat) == 2 * int(nproc)
    kw = {}
    if ckpt and resume:
        kw = dict(resume_from=ckpt)
    elif ckpt:
        kw = dict(checkpoint_path=ckpt, checkpoint_every_s=1.0)
    if pcap:
        kw["pcap_dir"] = pcap
    if digest:
        kw.update(digest=digest, digest_every=8)
    r = Simulation(scen, engine_cfg=cfg).run(mesh=mesh, **kw)
    if int(pid) == 0:
        np.save(out, r.stats)
    print(f"proc {pid}: {r.events} events", flush=True)


if __name__ == "__main__":
    main()
