"""Hand-built xplane protobuf buffers for the passcope decoder tests.

The ENCODER side of obs/passcope.py's wire decoder: enough of the
XSpace/XPlane/XLine/XEvent (+ embedded HloProto) schema to build
fixture traces byte-by-byte, so the decoder's varint/field walk is
tested against known wire bytes, not against itself round-tripping.
Also generates the committed CI fixture:

    python tests/helpers/xplane_encode.py tests/data/passcope_fixture.xplane.pb
"""

from __future__ import annotations


def varint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(fn: int, wt: int) -> bytes:
    return varint((fn << 3) | wt)


def f_varint(fn: int, v: int) -> bytes:
    return tag(fn, 0) + varint(v)


def f_bytes(fn: int, payload: bytes) -> bytes:
    return tag(fn, 2) + varint(len(payload)) + payload


def f_str(fn: int, s: str) -> bytes:
    return f_bytes(fn, s.encode())


# --- HloProto (the /host:metadata embed) ----------------------------------

def hlo_instruction(name: str, op_name: str | None) -> bytes:
    meta = f_str(2, op_name) if op_name else b""
    return f_str(1, name) + (f_bytes(7, meta) if op_name else b"")


def hlo_module(instrs) -> bytes:
    """instrs: [(hlo_name, op_name|None)] — one computation."""
    comp = b"".join(f_bytes(2, hlo_instruction(n, op))
                    for n, op in instrs)
    return f_bytes(3, comp)                    # HloModuleProto.computations


def hlo_proto(instrs) -> bytes:
    return f_bytes(1, hlo_module(instrs))      # HloProto.hlo_module


# --- XSpace ----------------------------------------------------------------

def xevent(mid: int, offset_ps: int, dur_ps: int) -> bytes:
    return f_varint(1, mid) + f_varint(2, offset_ps) + f_varint(3, dur_ps)


def xline(name: str, events) -> bytes:
    """events: [(mid, offset_ps, dur_ps)]."""
    return f_str(2, name) + b"".join(
        f_bytes(4, xevent(*e)) for e in events)


def xevent_metadata(name: str = "", stats_bytes: bytes = b"") -> bytes:
    out = f_str(2, name) if name else b""
    if stats_bytes:
        out += f_bytes(5, f_bytes(6, stats_bytes))  # stats -> bytes_value
    return out


def xplane(name: str, meta: dict, lines) -> bytes:
    """meta: {mid: metadata_bytes}; lines: [line_bytes]."""
    out = f_str(2, name)
    for mid, m in meta.items():
        out += f_bytes(4, f_varint(1, mid) + f_bytes(2, m))
    for ln in lines:
        out += f_bytes(3, ln)
    return out


def xspace(planes) -> bytes:
    return b"".join(f_bytes(1, p) for p in planes)


# --- the CI fixture --------------------------------------------------------

def make_fixture() -> bytes:
    """One traced chunk, numbers chosen for exact assertions
    (obs.passcope.self_check):

    device self-times (ms): fusion.1=40 (drain/w512), sort.2=30
    (exchange, under w512 via the window gather), custom-call.3=20
    (drain/w512/nic.rx_admit/tcp.rx), reduce.4=5 (advance),
    copy.5=3 (no scope -> residual), thunk parent glue=2 (runtime
    scaffolding, excluded from the denominator) -> HLO total 98,
    attributed 95/98.
    """
    ms = 10**9  # picoseconds per millisecond
    instrs = [
        ("fusion.1", "jit(run_windows)/jit(main)/drain/w512/while/body/gather"),
        ("sort.2", "jit(run_windows)/jit(main)/drain/w512/exchange/sort"),
        ("custom-call.3",
         "jit(run_windows)/jit(main)/drain/w512/nic.rx_admit/tcp.rx/fusion"),
        ("reduce.4", "jit(run_windows)/jit(main)/advance/reduce"),
        ("copy.5", None),            # no scope -> exercises the residual
    ]
    meta_plane = xplane(
        "/host:metadata",
        {1: xevent_metadata("jit_run_windows(1)", hlo_proto(instrs))},
        [])
    op_meta = {
        10: xevent_metadata("ThunkExecutor::Execute"),
        11: xevent_metadata("fusion.1"),
        12: xevent_metadata("sort.2"),
        13: xevent_metadata("custom-call.3"),
        14: xevent_metadata("reduce.4"),
        15: xevent_metadata("copy.5"),
    }
    # one parent thunk span [0,100ms) with nested op spans; parent
    # SELF time = 100-40-30-20-5-3 = 2ms of glue -> runtime bucket
    # (the "::" name rule); copy.5 is a real HLO op with no scope
    # -> the labeled residual
    events = [
        (10, 0 * ms, 100 * ms),
        (11, 0 * ms, 40 * ms),
        (12, 40 * ms, 30 * ms),
        (13, 70 * ms, 20 * ms),
        (14, 90 * ms, 5 * ms),
        (15, 95 * ms, 3 * ms),
    ]
    cpu_plane = xplane(
        "/host:CPU", op_meta,
        [xline("tf_XLATfrtCpuClient/271", events),
         xline("python-thread", [(10, 0, 50)])])  # non-XLA: ignored
    return xspace([meta_plane, cpu_plane])


if __name__ == "__main__":
    import sys
    out = sys.argv[1]
    with open(out, "wb") as f:
        f.write(make_fixture())
    print(f"wrote {out} ({len(make_fixture())} bytes)")
