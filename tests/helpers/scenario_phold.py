"""Shared tiny PHOLD scenario for the distributed test: built
identically by the worker processes and the comparing test process."""

TOPO = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="packetloss" attr.type="double" for="node" id="d0"/>
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4"/>
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3"/>
  <graph edgedefault="undirected">
    <node id="poi"><data key="d0">0.0</data>
      <data key="d3">10240</data><data key="d4">10240</data></node>
    <edge source="poi" target="poi"><data key="d7">20.0</data>
      <data key="d9">0.0</data></edge>
  </graph>
</graphml>"""

N_HOSTS = 4


def make_scenario(pcap=False):
    from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario

    return Scenario(
        stop_time=3 * 10**9,
        topology_graphml=TOPO,
        hosts=[HostSpec(id="node", quantity=N_HOSTS, pcap=pcap,
                        processes=[
            ProcessSpec(plugin="phold", start_time=10**9,
                        arguments="port=9000 mean=200ms size=64 init=1")])],
    )


def make_cfg():
    from shadow_tpu.engine.state import EngineConfig

    return EngineConfig(num_hosts=N_HOSTS, qcap=16, scap=4, obcap=8,
                        incap=16, chunk_windows=8, app_kinds=(0, 3),
                        uses_tcp=False)
