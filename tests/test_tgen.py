"""tgen behavior-graph tests.

Mirrors the reference's canonical example workload
(resource/examples/shadow.config.xml: tgen servers + web/bulk clients
walking GraphML behavior graphs) at reduced scale.
"""

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.apps.tgen import (TgenTables, parse_size, NK_START,
                                  NK_TRANSFER, NK_PAUSE, NK_END,
                                  COL_KIND, COL_A, COL_B, COL_NEXT)

SERVER_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="serverport" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start"><data key="d0">30080</data></node>
  </graph>
</graphml>"""

# web-style client: GET 50 KiB, short random pause, 3 rounds
WEB_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="count" attr.type="string" for="node" id="d6" />
  <key attr.name="size" attr.type="string" for="node" id="d5" />
  <key attr.name="type" attr.type="string" for="node" id="d4" />
  <key attr.name="protocol" attr.type="string" for="node" id="d3" />
  <key attr.name="time" attr.type="string" for="node" id="d2" />
  <key attr.name="peers" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start">
      <data key="d0">server1:30080,server2:30080</data>
    </node>
    <node id="pause"><data key="d2">1,2</data></node>
    <node id="transfer">
      <data key="d3">tcp</data><data key="d4">get</data>
      <data key="d5">50 KiB</data>
    </node>
    <node id="end"><data key="d6">3</data></node>
    <edge source="start" target="transfer" />
    <edge source="end" target="pause" />
    <edge source="pause" target="start" />
    <edge source="transfer" target="end" />
  </graph>
</graphml>"""

# bulk-style client: PUT 200 KiB back-to-back, 2 rounds
BULK_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="count" attr.type="string" for="node" id="d5" />
  <key attr.name="size" attr.type="string" for="node" id="d4" />
  <key attr.name="type" attr.type="string" for="node" id="d3" />
  <key attr.name="peers" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start">
      <data key="d0">server1:30080,server2:30080</data>
    </node>
    <node id="transfer">
      <data key="d3">put</data><data key="d4">200 KiB</data>
    </node>
    <node id="end"><data key="d5">2</data></node>
    <edge source="start" target="transfer" />
    <edge source="transfer" target="end" />
    <edge source="end" target="start" />
  </graph>
</graphml>"""


def tgen_scenario(topology, n_web=2, n_bulk=1, stop=60):
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=topology,
        hosts=[
            HostSpec(id="server", quantity=2, processes=[
                ProcessSpec(plugin="tgen", start_time=10**9,
                            arguments=SERVER_GRAPH)]),
            HostSpec(id="web", quantity=n_web, processes=[
                ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                            arguments=WEB_GRAPH)]),
            HostSpec(id="bulk", quantity=n_bulk, processes=[
                ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                            arguments=BULK_GRAPH)]),
        ],
    )


def test_parse_size():
    assert parse_size("100 KiB") == 102400
    assert parse_size("1 MiB") == 1 << 20
    assert parse_size("5242880") == 5242880
    assert parse_size("1.5 KB") == 1500


def test_graph_compile(simple_topology_xml):
    from shadow_tpu.routing.dns import DNS
    dns = DNS()
    for i, name in enumerate(["server1", "server2"]):
        dns.register(i, name, None)
    tab = TgenTables()
    start = tab.compile(WEB_GRAPH, dns)
    nodes, peers, pool = tab.arrays()
    assert nodes.shape == (4, 8)
    assert nodes[start, COL_KIND] == NK_START
    kinds = set(nodes[:, COL_KIND].tolist())
    assert kinds == {NK_START, NK_TRANSFER, NK_PAUSE, NK_END}
    # the cycle start -> transfer -> end -> pause -> start is closed
    cur, seen = start, []
    for _ in range(4):
        seen.append(int(nodes[cur, COL_KIND]))
        cur = int(nodes[cur, COL_NEXT])
    assert cur == start
    assert seen == [NK_START, NK_TRANSFER, NK_END, NK_PAUSE]
    # peers resolved; 2-second pause pool
    assert peers.shape == (2, 2)
    assert (peers[:, 1] == 30080).all()
    assert pool.tolist() == [10**9, 2 * 10**9]
    # dedup: same source compiles once
    assert tab.compile(WEB_GRAPH, dns) == start
    assert len(tab.nodes) == 4


def test_tgen_web_and_bulk_complete(simple_topology_xml):
    sim = Simulation(tgen_scenario(simple_topology_xml))
    report = sim.run()
    s = report.summary()
    stats = report.stats

    # client transfers: 2 web x 3 GETs + 1 bulk x 2 PUTs = 8 completions
    web = slice(2, 4)
    bulk = slice(4, 5)
    assert (stats[web, defs.ST_XFER_DONE] == 3).all(), stats[:, defs.ST_XFER_DONE]
    assert (stats[bulk, defs.ST_XFER_DONE] == 2).all(), stats[:, defs.ST_XFER_DONE]
    # every client reached its end node
    assert (stats[2:, defs.ST_APP_DONE] >= 1).all()
    # web clients actually received their GET payloads
    assert (stats[web, defs.ST_BYTES_RECV] >= 3 * 50 * 1024).all()
    # servers received the bulk PUT bytes
    assert stats[0:2, defs.ST_BYTES_RECV].sum() >= 2 * 200 * 1024
    assert s["drop_net"] == 0


def test_tgen_deterministic(simple_topology_xml):
    r1 = Simulation(tgen_scenario(simple_topology_xml)).run()
    r2 = Simulation(tgen_scenario(simple_topology_xml)).run()
    assert np.array_equal(r1.stats, r2.stats)
