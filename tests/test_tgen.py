"""tgen behavior-graph tests.

Mirrors the reference's canonical example workload
(resource/examples/shadow.config.xml: tgen servers + web/bulk clients
walking GraphML behavior graphs) at reduced scale.
"""

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.apps.tgen import (TgenTables, parse_size, NK_START,
                                  NK_TRANSFER, NK_PAUSE, NK_END, NK_SYNC,
                                  COL_KIND, COL_A, COL_B, COL_NEXT,
                                  COL_EOFF, COL_ECNT, NODE_COLS)

SERVER_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="serverport" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start"><data key="d0">30080</data></node>
  </graph>
</graphml>"""

# web-style client: GET 50 KiB, short random pause, 3 rounds
WEB_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="count" attr.type="string" for="node" id="d6" />
  <key attr.name="size" attr.type="string" for="node" id="d5" />
  <key attr.name="type" attr.type="string" for="node" id="d4" />
  <key attr.name="protocol" attr.type="string" for="node" id="d3" />
  <key attr.name="time" attr.type="string" for="node" id="d2" />
  <key attr.name="peers" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start">
      <data key="d0">server1:30080,server2:30080</data>
    </node>
    <node id="pause"><data key="d2">1,2</data></node>
    <node id="transfer">
      <data key="d3">tcp</data><data key="d4">get</data>
      <data key="d5">50 KiB</data>
    </node>
    <node id="end"><data key="d6">3</data></node>
    <edge source="start" target="transfer" />
    <edge source="end" target="pause" />
    <edge source="pause" target="start" />
    <edge source="transfer" target="end" />
  </graph>
</graphml>"""

# bulk-style client: PUT 200 KiB back-to-back, 2 rounds
BULK_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="count" attr.type="string" for="node" id="d5" />
  <key attr.name="size" attr.type="string" for="node" id="d4" />
  <key attr.name="type" attr.type="string" for="node" id="d3" />
  <key attr.name="peers" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start">
      <data key="d0">server1:30080,server2:30080</data>
    </node>
    <node id="transfer">
      <data key="d3">put</data><data key="d4">200 KiB</data>
    </node>
    <node id="end"><data key="d5">2</data></node>
    <edge source="start" target="transfer" />
    <edge source="transfer" target="end" />
    <edge source="end" target="start" />
  </graph>
</graphml>"""


def tgen_scenario(topology, n_web=2, n_bulk=1, stop=60):
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=topology,
        hosts=[
            HostSpec(id="server", quantity=2, processes=[
                ProcessSpec(plugin="tgen", start_time=10**9,
                            arguments=SERVER_GRAPH)]),
            HostSpec(id="web", quantity=n_web, processes=[
                ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                            arguments=WEB_GRAPH)]),
            HostSpec(id="bulk", quantity=n_bulk, processes=[
                ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                            arguments=BULK_GRAPH)]),
        ],
    )


def test_parse_size():
    assert parse_size("100 KiB") == 102400
    assert parse_size("1 MiB") == 1 << 20
    assert parse_size("5242880") == 5242880
    assert parse_size("1.5 KB") == 1500


def test_graph_compile(simple_topology_xml):
    from shadow_tpu.routing.dns import DNS
    dns = DNS()
    for i, name in enumerate(["server1", "server2"]):
        dns.register(i, name, None)
    tab = TgenTables()
    start = tab.compile(WEB_GRAPH, dns)
    nodes, peers, pool, edges = tab.arrays()
    assert nodes.shape == (4, NODE_COLS)
    assert nodes[start, COL_KIND] == NK_START
    kinds = set(nodes[:, COL_KIND].tolist())
    assert kinds == {NK_START, NK_TRANSFER, NK_PAUSE, NK_END}
    # the cycle start -> transfer -> end -> pause -> start is closed
    cur, seen = start, []
    for _ in range(4):
        seen.append(int(nodes[cur, COL_KIND]))
        cur = int(nodes[cur, COL_NEXT])
    assert cur == start
    assert seen == [NK_START, NK_TRANSFER, NK_END, NK_PAUSE]
    # peers resolved; 2-second pause pool
    assert peers.shape == (2, 2)
    assert (peers[:, 1] == 30080).all()
    assert pool.tolist() == [10**9, 2 * 10**9]
    # dedup: same source compiles once
    assert tab.compile(WEB_GRAPH, dns) == start
    assert len(tab.nodes) == 4


def test_tgen_web_and_bulk_complete(simple_topology_xml):
    sim = Simulation(tgen_scenario(simple_topology_xml))
    report = sim.run()
    s = report.summary()
    stats = report.stats

    # client transfers: 2 web x 3 GETs + 1 bulk x 2 PUTs = 8 completions
    web = slice(2, 4)
    bulk = slice(4, 5)
    assert (stats[web, defs.ST_XFER_DONE] == 3).all(), stats[:, defs.ST_XFER_DONE]
    assert (stats[bulk, defs.ST_XFER_DONE] == 2).all(), stats[:, defs.ST_XFER_DONE]
    # every client reached its end node
    assert (stats[2:, defs.ST_APP_DONE] >= 1).all()
    # web clients actually received their GET payloads
    assert (stats[web, defs.ST_BYTES_RECV] >= 3 * 50 * 1024).all()
    # servers received the bulk PUT bytes
    assert stats[0:2, defs.ST_BYTES_RECV].sum() >= 2 * 200 * 1024
    assert s["drop_net"] == 0


def test_tgen_deterministic(simple_topology_xml):
    r1 = Simulation(tgen_scenario(simple_topology_xml)).run()
    r2 = Simulation(tgen_scenario(simple_topology_xml)).run()
    assert np.array_equal(r1.stats, r2.stats)


# fork: start fans out to TWO parallel transfers; synchronize joins them
# before end counts a round (reference tgen multi-edge walk +
# synchronize action, shd-tgen-graph.c / shd-tgen-action.c)
FORK_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="count" attr.type="string" for="node" id="d6" />
  <key attr.name="size" attr.type="string" for="node" id="d5" />
  <key attr.name="type" attr.type="string" for="node" id="d4" />
  <key attr.name="peers" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start">
      <data key="d0">server1:30080,server2:30080</data>
    </node>
    <node id="transfer1">
      <data key="d4">get</data><data key="d5">10 KiB</data>
    </node>
    <node id="transfer2">
      <data key="d4">get</data><data key="d5">20 KiB</data>
    </node>
    <node id="synchronize" />
    <node id="end"><data key="d6">4</data></node>
    <edge source="start" target="transfer1" />
    <edge source="start" target="transfer2" />
    <edge source="transfer1" target="synchronize" />
    <edge source="transfer2" target="synchronize" />
    <edge source="synchronize" target="end" />
    <edge source="end" target="start" />
  </graph>
</graphml>"""


def test_fork_graph_compile(simple_topology_xml):
    from shadow_tpu.routing.dns import DNS
    dns = DNS()
    for i, name in enumerate(["server1", "server2"]):
        dns.register(i, name, None)
    tab = TgenTables()
    start = tab.compile(FORK_GRAPH, dns)
    nodes, peers, pool, edges = tab.arrays()
    assert nodes.shape == (5, NODE_COLS)
    # start has two out-edges (the fork)
    assert nodes[start, COL_ECNT] == 2
    s_eoff = nodes[start, COL_EOFF]
    forks = edges[s_eoff:s_eoff + 2].tolist()
    assert sorted(nodes[f, COL_KIND] for f in forks) == [NK_TRANSFER,
                                                         NK_TRANSFER]
    # synchronize has indegree 2
    sync = [i for i in range(5) if nodes[i, COL_KIND] == NK_SYNC][0]
    assert nodes[sync, COL_A] == 2
    assert tab.sync_slots == 1


def test_tgen_fork_and_synchronize(simple_topology_xml):
    """Both forked transfers complete each round; synchronize fires only
    after BOTH arrive; 2 rounds x 2 transfers = 4 completions."""
    scen = Scenario(
        stop_time=60 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", quantity=2, processes=[
                ProcessSpec(plugin="tgen", start_time=10**9,
                            arguments=SERVER_GRAPH)]),
            HostSpec(id="client", quantity=2, processes=[
                ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                            arguments=FORK_GRAPH)]),
        ],
    )
    report = Simulation(scen).run()
    stats = report.stats
    clients = slice(2, 4)
    # each client: 2 rounds of (2 parallel GETs + sync join) = 4 xfers
    assert (stats[clients, defs.ST_XFER_DONE] == 4).all(), \
        stats[:, defs.ST_XFER_DONE]
    assert (stats[clients, defs.ST_APP_DONE] == 1).all()
    # both payloads arrived each round: 2 x (10 + 20) KiB
    assert (stats[clients, defs.ST_BYTES_RECV] >=
            2 * (10 + 20) * 1024).all()
    # no walk branches were lost to cursor-stack overflow
    assert (stats[:, defs.ST_TGEN_DROP] == 0).all()


def test_tgen_nonblocking_cycle_rejected():
    from shadow_tpu.routing.dns import DNS
    dns = DNS()
    dns.register(0, "server1", None)
    bad = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
      <graph edgedefault="directed">
        <node id="start" />
        <node id="pause"><data key="time">0</data></node>
        <node id="end" />
        <edge source="start" target="pause" />
        <edge source="pause" target="end" />
        <edge source="end" target="pause" />
      </graph>
    </graphml>"""
    tab = TgenTables()
    with pytest.raises(ValueError, match="cycle never blocks"):
        tab.compile(bad, dns)


# --- transfer timeout / stallout (shd-tgen-transfer.c:918-961) -------------

# client whose first GET targets a host with no listener: nothing ever
# answers the SYN, so only the watchdog timeout can unstick the walk;
# the second GET targets a live server and must still complete
TIMEOUT_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="count" attr.type="string" for="node" id="d6" />
  <key attr.name="size" attr.type="string" for="node" id="d5" />
  <key attr.name="type" attr.type="string" for="node" id="d4" />
  <key attr.name="timeout" attr.type="string" for="node" id="d2" />
  <key attr.name="peers" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start">
      <data key="d0">dead:30080</data>
    </node>
    <node id="transfer1">
      <data key="d4">get</data><data key="d5">10 KiB</data>
      <data key="d2">2</data>
    </node>
    <node id="transfer2">
      <data key="d4">get</data><data key="d5">10 KiB</data>
      <data key="d0">server1:30080</data>
    </node>
    <node id="end"><data key="d6">1</data></node>
    <edge source="start" target="transfer1" />
    <edge source="transfer1" target="transfer2" />
    <edge source="transfer2" target="end" />
  </graph>
</graphml>"""


def test_tgen_timeout_parse(simple_topology_xml):
    """timeout/stallout compile into the transfer node row, with the
    reference's defaults when unset (shd-tgen-transfer.c:9-11)."""
    from shadow_tpu.apps.tgen import (COL_C, COL_REF,
                                      DEFAULT_XFER_TIMEOUT_NS,
                                      DEFAULT_XFER_STALLOUT_NS)
    from shadow_tpu.routing.dns import DNS
    dns = DNS()
    dns.register(0, "server1", None)
    dns.register(1, "dead", None)
    tab = TgenTables()
    tab.compile(TIMEOUT_GRAPH, dns)
    nodes, _, _, _ = tab.arrays()
    xfers = nodes[nodes[:, COL_KIND] == NK_TRANSFER]
    assert set(xfers[:, COL_C].tolist()) == {2 * 10**9,
                                            DEFAULT_XFER_TIMEOUT_NS}
    assert (xfers[:, COL_REF] == DEFAULT_XFER_STALLOUT_NS).all()


def test_tgen_timeout_aborts_and_walk_continues(simple_topology_xml):
    """A GET whose peer never answers aborts at its 2s timeout (counted
    in ST_TGEN_ABORT), and the walk proceeds to the next transfer,
    which completes (the reference's wasSuccess=FALSE notify +
    continueNextActions, shd-tgen-driver.c:55-72)."""
    scen = Scenario(
        stop_time=30 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server1", processes=[
                ProcessSpec(plugin="tgen", start_time=10**9,
                            arguments=SERVER_GRAPH)]),
            HostSpec(id="dead"),   # attached, resolvable, no listener
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                            arguments=TIMEOUT_GRAPH)]),
        ],
    )
    report = Simulation(scen).run()
    stats = report.stats
    cli = 2
    assert stats[cli, defs.ST_TGEN_ABORT] == 1
    assert stats[cli, defs.ST_XFER_DONE] == 1       # only transfer2
    # the abort did NOT count toward the end condition; the successful
    # transfer2 did, so the walk ends with exactly count=1
    assert stats[cli, defs.ST_APP_DONE] == 1
    assert report.summary()["transfers_aborted"] == 1
    # the client actually received transfer2's payload
    assert stats[cli, defs.ST_BYTES_RECV] >= 10 * 1024


def test_tgen_stallout_unit(simple_topology_xml):
    """Row-level watchdog check: same progress mark across a full
    stallout period aborts (reference stall rule lastProgress > 0 &&
    now >= lastProgress + stallout); advancing progress re-arms."""
    import jax
    import jax.numpy as jnp
    from shadow_tpu.apps.tgen import app_tgen, WD_AUX, COL_C, COL_REF
    from shadow_tpu.engine.defs import WAKE_TIMER
    from shadow_tpu.net import packet as P
    from shadow_tpu.net.socket import TCPS_ESTABLISHED
    from shadow_tpu.core.simtime import SIMTIME_MAX

    scen = Scenario(
        stop_time=30 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server1", processes=[
                ProcessSpec(plugin="tgen", start_time=10**9,
                            arguments=SERVER_GRAPH)]),
            HostSpec(id="dead"),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                            arguments=TIMEOUT_GRAPH)]),
        ],
    )
    sim = Simulation(scen)
    sh = sim.sh
    nodes = np.asarray(sh.tgen_nodes)
    # the long-timeout transfer node (transfer2: default 60s timeout)
    node = int(np.nonzero((nodes[:, COL_KIND] == NK_TRANSFER) &
                          (nodes[:, COL_C] == 60 * 10**9))[0][0])
    cli = 2
    row = jax.tree.map(lambda x: x[cli], sim.hosts)
    hpr = jax.tree.map(lambda x: x[cli], sim.hp)
    # apps receive the single-PROCESS view of the [P]-shaped app state
    # (engine.window._on_app builds it; unit calls build it here)
    row = row.replace(app_node=row.app_node[0], app_r=row.app_r[0])
    hpr = hpr.replace(app_kind=hpr.app_kind[0], app_cfg=hpr.app_cfg[0])
    slot = 0
    row = row.replace(
        sk_used=row.sk_used.at[slot].set(True),
        sk_proto=row.sk_proto.at[slot].set(P.PROTO_TCP),
        sk_state=row.sk_state.at[slot].set(TCPS_ESTABLISHED),
        sk_app_ref=row.sk_app_ref.at[slot].set(node),
        sk_rcv_nxt=row.sk_rcv_nxt.at[slot].set(5000),
        sk_hs_time=row.sk_hs_time.at[slot].set(10**9),
    )
    gen = int(row.sk_timer_gen[slot])

    def wd_wake(mark):
        w = np.zeros(P.PKT_WORDS, np.int32)
        w[P.ACK] = WAKE_TIMER
        w[P.SEQ] = slot
        w[P.AUX] = WD_AUX
        w[P.WND] = gen
        w[P.LEN] = mark
        return jnp.asarray(w)

    now = 20 * 10**9
    # no progress since the mark -> abort + walk continues (the
    # successor is the end node; count unmet so no APP_DONE)
    r2 = app_tgen(row, hpr, sh, jnp.int64(now), wd_wake(5000))
    assert int(r2.stats[defs.ST_TGEN_ABORT]) == 1
    assert int(r2.sk_app_ref[slot]) == -1

    # progress advanced since the mark -> no abort, watchdog re-armed
    r3 = app_tgen(row, hpr, sh, jnp.int64(now), wd_wake(1000))
    assert int(r3.stats[defs.ST_TGEN_ABORT]) == 0
    assert int(r3.sk_app_ref[slot]) == node
    q_before = int((np.asarray(row.eq_time) != SIMTIME_MAX).sum())
    q_after = int((np.asarray(r3.eq_time) != SIMTIME_MAX).sum())
    assert q_after == q_before + 1
    # re-armed one stallout period out (the queue's new entry)
    before = np.asarray(row.eq_time)
    after = np.asarray(r3.eq_time)
    new_times = after[after != before]
    assert new_times.tolist() == [now + int(nodes[node, COL_REF])]

    # stale generation (recycled slot) -> watchdog is a no-op
    w = np.asarray(wd_wake(5000)).copy()
    w[P.WND] = gen + 5
    r4 = app_tgen(row, hpr, sh, jnp.int64(now), jnp.asarray(w))
    assert int(r4.stats[defs.ST_TGEN_ABORT]) == 0
    assert int(r4.sk_app_ref[slot]) == node
