"""Network observatory (obs.netscope) acceptance tests.

The contract under test (docs/observability.md "Network
observatory"):

- the device histograms are EXACT: bit-equal to the pure-Python
  reference engine recounting the same samples on the differential
  scenarios (the same oracle the stats table answers to);
- observation never perturbs simulation: a netscope run's
  non-netscope digest sections are byte-equal to the same seed run
  with the knob off, and same-seed netscope runs are byte-identical
  end to end (digest chain AND the JSONL time-series);
- vmapped batch lanes are exactly their individual runs: per-lane
  reports and per-lane JSONL streams byte-match, and the cross-lane
  ensemble pools them;
- the host-side math (bucket ladder, exact percentiles, fold,
  ensemble) agrees with the device bucketing;
- the heartbeat/stream tooling round-trips (tools/parse_heartbeat.py
  columns == obs.tracker line schema, rss=/dev= and netscope CSV).
"""

import importlib.util
import json
import os
import sys

import numpy as np

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.pyengine import PyEngine
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig, hot_fields
from shadow_tpu.obs import netscope as NS

from conftest import SIMPLE_TOPOLOGY
from test_differential import CFG, _bulk_scen

NCFG = dict(CFG, netscope=True)


def _bulk_cfg(netscope=True):
    return EngineConfig(num_hosts=2, **(NCFG if netscope else CFG))


def _bulk():
    # lossy TCP bulk: populates completion (app), queue (NIC admit)
    # and retx (RTO) — the richest single differential shape
    return _bulk_scen(loss=0.05, size=120_000, count=2, stop=60)()


def _ping():
    return Scenario(
        stop_time=8 * 10**9,
        topology_graphml=SIMPLE_TOPOLOGY,
        hosts=[
            HostSpec(id="srv", processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="cli", processes=[
                ProcessSpec(plugin="ping", start_time=2 * 10**9,
                            arguments="peer=srv port=8000 "
                                      "interval=700ms size=96 "
                                      "count=6")]),
        ],
    )


# --- host-side math (no engine) --------------------------------------


def test_bucket_ladder_host_equals_device_rule():
    # the device bucketing is sum(v >= bounds); bucket_of must agree
    # on every edge and both sides of it
    for v in (0, 1, 2, 3, 4, 1023, 1024, 1025, 1500,
              (1 << 30) - 1, 1 << 30, 1 << 40):
        assert NS.bucket_of(v) == sum(v >= b for b in NS.BOUNDS_US), v
    assert len(NS.BOUNDS_US) == NS.NS_BUCKETS - 1
    assert NS.bucket_edge_us(0) == 1
    assert NS.bucket_edge_us(11) == 2048
    assert NS.bucket_edge_us(NS.NS_BUCKETS - 1) == 1 << 31


def test_percentile_exact_ranks():
    row = [0] * NS.NS_BUCKETS
    row[5] = 99
    row[20] = 1
    assert NS.percentile(row, 50) == 1 << 5
    assert NS.percentile(row, 99) == 1 << 5     # rank 99 of 100
    assert NS.percentile(row, 100) == 1 << 20
    assert NS.percentile([0] * NS.NS_BUCKETS, 99) == 0
    s = NS.kind_summary(row)
    assert s["count"] == 100 and s["p99_us"] == 1 << 5


def test_fold_and_ensemble():
    t = [[(i + 1) * (j + 1) for j in range(NS.NS_BUCKETS)]
         for i in range(NS.NS_KINDS)]
    assert NS.fold(t) == t
    assert NS.fold([t, t, t]) == [[3 * c for c in r] for r in t]
    assert NS.fold([[t], [t]]) == [[2 * c for c in r] for r in t]
    a = [[0] * NS.NS_BUCKETS for _ in range(NS.NS_KINDS)]
    b = [[0] * NS.NS_BUCKETS for _ in range(NS.NS_KINDS)]
    a[NS.NS_RTT][3] = 5
    b[NS.NS_RTT][9] = 5
    ens = NS.ensemble([a, b])
    r = ens["kinds"]["rtt"]
    assert r["count"] == 10
    assert r["lane_p99_us"] == [1 << 3, 1 << 9]
    assert abs(r["cdf"][-1] - 1.0) < 1e-9
    assert ens["runs"] == 2


# --- state contract ---------------------------------------------------


def test_netscope_knob_is_shape_and_hot_set():
    on, off = _bulk_cfg(True), _bulk_cfg(False)
    ha = Simulation(_bulk(), engine_cfg=on).hosts
    hb = Simulation(_bulk(), engine_cfg=off).hosts
    assert ha.ns_hist.shape == (2, NS.NS_KINDS, NS.NS_BUCKETS)
    assert hb.ns_hist.shape == (2, NS.NS_KINDS, 0)
    assert "ns_hist" in hot_fields(on)
    assert "ns_hist" not in hot_fields(off)


# --- exactness vs the reference engine --------------------------------


def test_device_hist_equals_pyengine_bulk():
    cfg = _bulk_cfg()
    rep = Simulation(_bulk(), engine_cfg=cfg).run()
    py = PyEngine(Simulation(_bulk(), engine_cfg=cfg))
    py.run()
    # the run's report reads the FINAL device histograms; the
    # reference engine recounts the same samples in pure Python —
    # every kind, every bucket, bit-equal
    ref = NS.fold(py.ns_hist.tolist())
    dev = [rep.network["kinds"][n]["buckets"]
           for n in NS.KIND_NAMES]
    assert dev == ref, (
        f"device {[sum(r) for r in dev]} != "
        f"pyengine {[sum(r) for r in ref]}")
    # something actually happened in every expected kind
    per_kind = [sum(r) for r in dev]
    assert per_kind[NS.NS_COMPLETION] == 2     # count=2 transfers
    assert per_kind[NS.NS_QUEUE] > 0
    assert per_kind[NS.NS_RETX] > 0            # 5% loss forces RTOs
    k = rep.network["kinds"]
    assert k["queue"]["count"] == per_kind[NS.NS_QUEUE]
    s = rep.summary()
    assert s["rtt_p50_us"] == k["rtt"]["p50_us"]
    assert s["completion_p99_s"] == k["completion"]["p99_us"] / 1e6


def test_device_hist_equals_pyengine_ping_rtt():
    cfg = _bulk_cfg()
    rep = Simulation(_ping(), engine_cfg=cfg).run()
    py = PyEngine(Simulation(_ping(), engine_cfg=cfg))
    py.run()
    dev = [rep.network["kinds"][n]["buckets"]
           for n in NS.KIND_NAMES]
    assert dev == NS.fold(py.ns_hist.tolist())
    # 6 echoes: each is an RTT sample and a completion sample
    assert sum(dev[NS.NS_RTT]) == 6
    assert sum(dev[NS.NS_COMPLETION]) == 6


# --- determinism and non-perturbation ---------------------------------


def test_same_seed_runs_byte_identical(tmp_path):
    outs = []
    for tag in ("a", "b"):
        dg = tmp_path / f"{tag}.digest.jsonl"
        ns = tmp_path / f"{tag}.netscope.jsonl"
        mt = tmp_path / f"{tag}.metrics.json"
        Simulation(_bulk(), engine_cfg=_bulk_cfg()).run(
            digest=str(dg), netscope=str(ns), metrics=str(mt))
        outs.append((dg.read_bytes(), ns.read_bytes(),
                     json.loads(mt.read_text())))
    assert outs[0][0] == outs[1][0], "digest chains differ"
    assert outs[0][1] == outs[1][1], "netscope streams differ"
    # the metrics net section is assembled and identical
    net = outs[0][2]["net"]
    assert net == outs[1][2]["net"]
    assert net["completion.count"] == 2
    assert isinstance(net["queue.bucket"], list)
    # the stream is self-describing and cumulative
    header, recs = NS.read_stream(str(tmp_path / "a.netscope.jsonl"))
    assert header["format"] == NS.FORMAT
    assert header["kinds"] == list(NS.KIND_NAMES)
    assert recs, "no chunk records"
    assert recs[-1]["hist"][NS.NS_COMPLETION][
        NS.bucket_of(1)] >= 0     # table shape holds
    tot = [sum(r) for r in recs[-1]["hist"]]
    assert tot[NS.NS_COMPLETION] == 2


def test_observation_does_not_perturb_digest(tmp_path):
    chains = {}
    for on in (True, False):
        p = tmp_path / f"ns-{on}.digest.jsonl"
        Simulation(_bulk(), engine_cfg=_bulk_cfg(on)).run(
            digest=str(p))
        chains[on] = [json.loads(line)
                      for line in p.read_text().splitlines()
                      if "sections" in line]
    on_recs = [r for r in chains[True] if "sections" in r]
    off_recs = [r for r in chains[False] if "sections" in r]
    assert len(on_recs) == len(off_recs)
    for a, b in zip(on_recs, off_recs):
        assert a["window"] == b["window"]
        sa = dict(a["sections"])
        sb = dict(b["sections"])
        # the netscope section exists exactly when the knob is on...
        assert "netscope" in sa and "netscope" not in sb
        del sa["netscope"]
        # ...and every OTHER section hash is byte-equal: observing
        # the run did not change a single simulated byte
        assert sa == sb, (a["window"], sa, sb)


# --- vmapped ensemble --------------------------------------------------


def test_batch_lanes_equal_individual_runs(tmp_path):
    from shadow_tpu.serving.batch import run_batch

    cfg = _bulk_cfg()
    seeds = [11, 12, 13, 14]

    def mk(seed):
        scen = _bulk()
        scen.seed = seed
        return Simulation(scen, engine_cfg=cfg)

    paths = [str(tmp_path / f"lane{s}.netscope.jsonl")
             for s in seeds]
    reports = run_batch([mk(s) for s in seeds],
                        names=[f"s{s}" for s in seeds],
                        netscope_paths=paths)
    for s, rep, p in zip(seeds, reports, paths):
        ind = tmp_path / f"ind{s}.netscope.jsonl"
        ind_rep = mk(s).run(netscope=str(ind))
        assert rep.network["kinds"] == ind_rep.network["kinds"], s
        assert (open(p, "rb").read() == ind.read_bytes()), (
            f"lane {s} stream != individual run stream")
    # cross-lane ensemble pools the lanes exactly
    ens = NS.ensemble([
        [r.network["kinds"][n]["buckets"] for n in NS.KIND_NAMES]
        for r in reports])
    assert ens["runs"] == len(seeds)
    assert (ens["kinds"]["completion"]["count"]
            == sum(r.network["kinds"]["completion"]["count"]
                   for r in reports))
    assert len(ens["kinds"]["rtt"]["lane_p99_us"]) == len(seeds)


# --- ledger tail fields ------------------------------------------------


def test_ledger_entry_carries_tails():
    from shadow_tpu.obs import ledger as LG
    e = LG.make_entry(
        "x", "0" * 16, "cpu",
        {"events": 1, "wall_seconds": 1.0, "events_per_sec": 1.0,
         "rtt_p50_us": 8, "rtt_p99_us": 4096,
         "completion_p99_s": 2.5})
    assert (e["rtt_p50_us"], e["rtt_p99_us"],
            e["completion_p99_s"]) == (8, 4096, 2.5)
    e2 = LG.make_entry(
        "x", "0" * 16, "cpu",
        {"events": 1, "wall_seconds": 1.0, "events_per_sec": 1.0})
    assert "rtt_p50_us" not in e2


# --- tooling round-trips -----------------------------------------------


def _tool(name):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        f"_{name}", os.path.join(repo, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_parse_heartbeat_matches_tracker_schema():
    from shadow_tpu.obs import tracker
    ph = _tool("parse_heartbeat")
    # the CSV columns ARE the tracker's [node] schema, including the
    # covered-interval column PR 15 added
    assert [f.replace("_", "-") for f in ph.FIELDS] == \
        tracker.HEADER.split(",")
    rows = ph.node_rows([
        "x [shadow-heartbeat] [node] 3,cli,1,7,2,1,0,64,0,0,0,0",
        "unrelated line"])
    assert rows == [["3", "cli", "1", "7", "2", "1", "0", "64",
                     "0", "0", "0", "0"]]
    # [ram] rows: optional rss= / dev= suffixes become fixed columns
    rows = ph.ram_rows([
        "x [shadow-heartbeat] [ram] 3,cli,10,0,10,1",
        "x [shadow-heartbeat] [ram] 4,cli,0,5,5,1,rss=777",
        "x [shadow-heartbeat] [ram] 5,cli,0,0,5,1,rss=778,dev=999",
    ])
    assert [r[len(r) - 2:] for r in rows] == [
        ["", ""], ["777", ""], ["778", "999"]]
    assert rows[2][:6] == ["5", "cli", "0", "0", "5", "1"]


def test_parse_heartbeat_netscope_roundtrip(tmp_path):
    ph = _tool("parse_heartbeat")
    rec = NS.NetScope(str(tmp_path / "s.jsonl"))
    hist = np.zeros((2, NS.NS_KINDS, NS.NS_BUCKETS), np.int64)
    stats = np.zeros((2, defs.N_STATS), np.int64)
    hist[0, NS.NS_RTT, 5] = 4
    stats[:, defs.ST_EVENTS] = 10
    rec.sample(8, 10**9, hist, stats, conns=3)
    hist[1, NS.NS_RTT, 9] = 4
    stats[:, defs.ST_EVENTS] = 25
    rec.sample(16, 2 * 10**9, hist, stats, conns=1)
    rec.close()
    fields, rows = ph.netscope_rows(str(tmp_path / "s.jsonl"))
    assert fields[:2] == ["window", "time"]
    assert "rtt_p99_us" in fields
    by = [dict(zip(fields, r)) for r in rows]
    assert by[0]["window"] == 8 and by[1]["window"] == 16
    assert by[0]["d_events"] == 20        # first delta is the total
    assert by[1]["d_events"] == 30
    assert by[0]["rtt_n"] == 4 and by[1]["rtt_n"] == 8
    assert by[0]["rtt_p99_us"] == 1 << 5
    assert by[1]["rtt_p99_us"] == 1 << 9  # pooled tail moved up


def test_netreport_self_check():
    nr = _tool("netreport")
    assert nr.self_check() == 0
